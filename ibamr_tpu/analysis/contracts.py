"""The artifact registry and its budgets (``GRAPH_BUDGETS.json``).

A *contract* pins one named compiled artifact — the solo step, the
fused spectral substep per dtype, each spread/interp engine, the
driver's scanned chunk, the lane-masked fleet chunk, the donated step,
the per-lane capsule fetch — to the budget-comparable slice of its
:func:`~ibamr_tpu.analysis.graph_census.graph_census`. Budgets live in
``GRAPH_BUDGETS.json`` at the repo root and are versioned with the
code: a refactor that adds a scatter, un-fuses an FFT, sneaks a host
transfer into the scan, widens a dtype, or silently drops donation
fails the gate (``tools/graph_audit.py``, exit 2) and the tier-1 pin
(``tests/test_graph_contracts.py``) on the same counting rules.

Measurement runs under ``jax.experimental.disable_x64()`` so the
numbers are the PRODUCTION (x64-off) graph regardless of caller
config — the pytest conftest enables x64 globally, and budgets must
not depend on which harness measured them.

Update workflow (see docs/ANALYSIS.md): change code, run
``python tools/graph_audit.py`` — exit 0 means no drift, exit 1 means
you improved a budgeted metric (run with ``--tighten`` to ratchet the
budget down and commit the diff), exit 2 names the regressed metrics.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field
from typing import Callable, Dict, Optional, Tuple

from ibamr_tpu.analysis.graph_census import (
    BUDGET_MAX_METRICS,
    BUDGET_MIN_METRICS,
    budget_metrics,
    graph_census,
)

REPO_ROOT = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))
BUDGET_PATH = os.path.join(REPO_ROOT, "GRAPH_BUDGETS.json")

# shared flagship-miniature shape: big enough that every structural
# feature of the graph exists (buckets, packing, scan, probe fusion),
# small enough that the whole registry compiles in seconds on CPU.
_N, _N_LAT, _N_LON = 16, 8, 16
_DT = 5e-5


def _shell(engine="packed", spectral_dtype=None):
    from ibamr_tpu.models.shell3d import build_shell_example

    return build_shell_example(
        n_cells=_N, n_lat=_N_LAT, n_lon=_N_LON, radius=0.25,
        aspect=1.2, stiffness=1.0, rest_length_factor=0.75, mu=0.05,
        use_fast_interaction=engine, spectral_dtype=spectral_dtype)


def _unwrap(jitted):
    """The raw python callable behind a ``jax.jit`` wrapper, so the
    census controls jit/donation itself instead of nesting pjit."""
    return getattr(jitted, "__wrapped__", jitted)


# ---------------------------------------------------------------------------
# artifact builders — each returns (fn, args, donate_argnums)
# ---------------------------------------------------------------------------

def _build_solo_step(spectral_dtype=None):
    integ, state = _shell(spectral_dtype=spectral_dtype)
    return (lambda s: integ.step(s, _DT)), (state,), ()


def _build_fused_substep(spectral_dtype=None):
    from ibamr_tpu.solvers import fft as _fft

    integ, state = _shell(spectral_dtype=spectral_dtype)
    ins = integ.ins
    dx = ins.grid.dx
    alpha, beta = ins.rho / _DT, -0.5 * ins.mu

    def sub(rhs):
        return _fft.helmholtz_project_periodic(
            rhs, dx, alpha=alpha, beta=beta,
            pinc_coeffs=(alpha, beta), spectral_dtype=spectral_dtype)

    return sub, (state.ins.u,), ()


def _build_transfer(engine, piece):
    import jax.numpy as jnp

    integ, state = _shell(engine=engine)
    ib = integ.ib
    grid = integ.ins.grid
    X, mask = state.X, state.mask
    if piece == "spread":
        F = jnp.zeros_like(X)

        def spread(Xa, Fa, m):
            ctx = ib.prepare(Xa, m)
            return ib.spread_force(Fa, grid, Xa, m, ctx=ctx)

        return spread, (X, F, mask), ()
    u = state.ins.u

    def interp(ua, Xa, m):
        ctx = ib.prepare(Xa, m)
        return ib.interpolate_velocity(ua, grid, Xa, m, ctx=ctx)

    return interp, (u, X, mask), ()


def _driver(integ, lanes=None, donate=False, lane_mesh=None, remat=None):
    from ibamr_tpu.utils.health import HealthProbe
    from ibamr_tpu.utils.hierarchy_driver import HierarchyDriver, RunConfig

    cfg = RunConfig(dt=_DT, num_steps=4, health_interval=2,
                    donate=donate, remat=remat)
    return HierarchyDriver(integ, cfg, lanes=lanes, lane_mesh=lane_mesh,
                           health_probe=HealthProbe.for_integrator(integ))


# -- gradient artifacts (PR 19): the adjoint-at-primal-cost pins ------------

def _build_grad_substep(spectral_dtype=None):
    # full jax.vjp round trip of the fused spectral substep. The custom
    # VJP rides the SAME plan (conjugate symbol application), so the
    # whole forward+backward pass is pinned at <= 2x the primal's
    # batched FFT calls (fft_ops 4 vs the primal's 2) — the headline
    # "adjoint at primal cost" budget.
    import jax
    import jax.numpy as jnp

    sub, (rhs,), _ = _build_fused_substep(spectral_dtype=spectral_dtype)
    out_shape = jax.eval_shape(sub, rhs)
    ct = jax.tree_util.tree_map(
        lambda s: jnp.ones(s.shape, s.dtype), out_shape)

    def grad_sub(r, c):
        out, vjpf = jax.vjp(sub, r)
        return out, vjpf(c)

    return grad_sub, (rhs, ct), ()


def _build_grad_transfer(piece):
    # the packed-transfer BACKWARD pass in isolation (the bwd rule the
    # custom VJP installs), with the buckets closure-captured exactly as
    # reverse-mode residuals are: zero bucket preps in the graph, and
    # for grad_spread zero scatter primitives — d(spread) is an interp
    # through the SAME PackedBuckets (gather-only overflow merge
    # included). grad_interp's d/df IS the primal spread (the adjoint
    # of a gather is a scatter); its budget pins that no NEW scatter
    # shapes appear beyond the primal set.
    import jax
    import jax.numpy as jnp

    from ibamr_tpu.ops import interaction_packed as ip

    integ, state = _shell(engine="packed")
    eng = integ.ib.fast
    X, mask = state.X, state.mask
    b = eng.buckets(X, mask)
    nd = (eng.geom, eng.grid, 0, eng.kernel,
          jax.lax.Precision.HIGHEST, None)
    if piece == "spread":
        F = jnp.zeros(X.shape[0], X.dtype)
        g = jnp.zeros(eng.grid.n, X.dtype)

        def spread_bwd(Fa, Xa, ga):
            return ip._spread_bwd(*nd, (b, Fa, Xa), ga)[1:]

        return spread_bwd, (F, X, g), ()
    f = jnp.zeros(eng.grid.n, X.dtype)
    ct = jnp.zeros(X.shape[0], X.dtype)

    def interp_bwd(fa, Xa, ca):
        return ip._interp_bwd(*nd, (b, fa, Xa), ca)[1:]

    return interp_bwd, (f, X, ct), ()


def _build_grad_chunk():
    # reverse mode through the driver's remat-checkpointed scan chunk
    # (RunConfig(remat=), health probe fused in): the design loop's
    # unit of differentiation. host_transfers_in_scan == 0 and
    # f64_widenings == 0 are the pins — the cotangent scan must stay as
    # device-resident and dtype-clean as the primal one.
    import jax
    import jax.numpy as jnp

    integ, state = _shell()
    drv = _driver(integ, remat="dots")
    chunk = _unwrap(drv._chunk(4))

    def grad_chunk(st, dt):
        def loss(s):
            leaves = jax.tree_util.tree_leaves(chunk(s, dt))
            return sum(jnp.sum(l) for l in leaves
                       if jnp.issubdtype(l.dtype, jnp.inexact))

        # allow_int: the state pytree carries int32 counters (step
        # index, refresh bookkeeping) that get symbolic-zero cotangents
        return jax.grad(loss, allow_int=True)(st)

    return grad_chunk, (state, _DT), ()


def _build_solo_chunk():
    # the driver's scanned chunk WITH the fused health probe — the
    # scan body is where a stray host transfer would be catastrophic
    # (one D2H per step instead of one per chunk)
    integ, state = _shell()
    drv = _driver(integ)
    chunk = _unwrap(drv._chunk(4))
    return chunk, (state, _DT), ()


def _build_donated_chunk():
    # cfg.donate=True chunk: the whole-step in-place update. The budget
    # pins donated_args >= 1 — donation is a REQUEST; this artifact is
    # where it is verified against the compiled alias table.
    integ, state = _shell()
    drv = _driver(integ, donate=True)
    chunk = _unwrap(drv._chunk(4))
    return chunk, (state, _DT), (0,)


def _build_fleet_chunk():
    import jax.numpy as jnp

    from ibamr_tpu.utils import lanes as _lanes

    integ, state = _shell()
    drv = _driver(integ, lanes=2)
    chunk = _unwrap(drv._chunk(2))
    stacked = _lanes.stack_lanes([state, state])
    dt_vec = jnp.full((2,), _DT, dtype=jnp.float32)
    alive = jnp.ones((2,), dtype=bool)
    return chunk, (stacked, dt_vec, alive), ()


def _attach_contract_ledger():
    """Attach a live run ledger for a telemetry-on artifact build. The
    ledger stays attached THROUGH the census trace (detached and closed
    by :func:`measure_artifact`'s finally), so the chunk is lowered in
    exactly the configuration a supervised run uses — if telemetry ever
    leaks a ``jax.debug.callback``/``io_callback`` into the traced
    chunk, ``host_transfers_in_scan`` catches it here."""
    import tempfile

    from ibamr_tpu import obs

    path = os.path.join(tempfile.mkdtemp(prefix="obs-contract-"),
                        "ledger.jsonl")
    obs.attach(obs.RunLedger(path))


def _build_solo_chunk_telemetry():
    # the solo chunk exactly as the instrumented driver runs it: live
    # ledger attached, the chunk call wrapped in the driver's span, the
    # per-chunk counter/watermark flush issued after — all of which
    # must stay HOST-side (same FFT/scatter ceilings as solo_chunk,
    # host_transfers_in_scan == 0)
    from ibamr_tpu import obs

    integ, state = _shell()
    drv = _driver(integ)
    chunk = _unwrap(drv._chunk(4))
    _attach_contract_ledger()

    def run(st, dt):
        with obs.span("driver/chunk", step=0, length=4):
            out = chunk(st, dt)
        obs.chunk_boundary(step=4)
        return out

    return run, (state, _DT), ()


def _build_fleet_chunk_telemetry():
    import jax.numpy as jnp

    from ibamr_tpu import obs
    from ibamr_tpu.utils import lanes as _lanes

    integ, state = _shell()
    drv = _driver(integ, lanes=2)
    chunk = _unwrap(drv._chunk(2))
    stacked = _lanes.stack_lanes([state, state])
    dt_vec = jnp.full((2,), _DT, dtype=jnp.float32)
    alive = jnp.ones((2,), dtype=bool)
    _attach_contract_ledger()

    def run(st, dt, al):
        with obs.span("driver/chunk", step=0, length=2):
            out = chunk(st, dt, al)
        obs.chunk_boundary(step=2)
        return out

    return run, (stacked, dt_vec, alive), ()


def _build_donated_step():
    # IBExplicitIntegrator.jitted_step(donate=True) unwrapped: verifies
    # the integrator-level donation request actually aliases buffers
    integ, state = _shell()
    step = _unwrap(integ.jitted_step(donate=True))
    return step, (state, _DT), (0,)


def _build_lane_fetch():
    # the per-lane capsule/rollback fetch graph: lane_slice of a
    # 2-lane stacked state (must be a pure gather-free slice — zero
    # scatters, zero FFTs, zero host ops)
    from ibamr_tpu.utils import lanes as _lanes

    integ, state = _shell()
    stacked = _lanes.stack_lanes([state, state])
    return (lambda st: _lanes.lane_slice(st, 0)), (stacked,), ()


def _build_open_channel_step():
    # open-boundary stabilized-PPM step: the non-periodic code path
    # (saddle Stokes + boundary-band upwind blending). First-wave
    # finding lived here (_stab_mask hard-coded f64); the budget pins
    # the path dtype-clean from now on.
    from ibamr_tpu.integrators.ins_open import INSOpenIntegrator
    from ibamr_tpu.solvers.stokes import channel_bc

    io = INSOpenIntegrator(
        (_N, _N), (1.0 / _N, 1.0 / _N), channel_bc(2), mu=0.05,
        dt=_DT, bdry={(0, 0, 0): 1.0},
        convective_op_type="stabilized_ppm")
    state = io.initialize()
    return (lambda s: io.step(s)), (state,), ()


def _build_served_chunk():
    # the warm-pool router's first-step ack: a 1-step 2-lane fleet
    # chunk with ONE live lane and one dead-on-arrival padding lane
    # (pad_lanes). The serving path must lower the same in-scan
    # structure as the batch fleet chunk — a padded request bucket
    # cannot buy extra host transfers or scatters
    from ibamr_tpu.serve.aot_cache import ExecutableCache
    from ibamr_tpu.serve.router import BucketSpec, WarmPool

    pool = WarmPool(BucketSpec(n_cells=_N, n_lat=_N_LAT, n_lon=_N_LON,
                               lanes=2, engine="packed"),
                    ExecutableCache())
    return pool.contract_args(length=1, live=1)


def _require_devices(jax, n=8):
    if len(jax.devices()) < n:
        raise RuntimeError(
            f"sharded artifact needs {n} devices (virtual CPU devices "
            f"count) — got {len(jax.devices())}; the audit child forces "
            f"force_cpu({n}) and the test conftest sets "
            f"--xla_force_host_platform_device_count=8")


def _build_sharded_chunk():
    # the pod driver's unit of work: the dispatched sharded coupled IB
    # step (pencil-FFT solves + S2 co-partitioned transfers) scanned
    # over a 2-step chunk on the 8-device mesh. The collective/overlap
    # metrics pinned here are the comm-layer contract of ROADMAP item 2
    # (sharded_speedup diagnosis): a refactor that adds a transpose,
    # doubles a halo, or un-hides an async pair regresses the budget.
    import jax

    from ibamr_tpu.parallel import make_mesh
    from ibamr_tpu.parallel.mesh import make_sharded_step, place_state

    _require_devices(jax)
    integ, state0 = _shell()
    mesh = make_mesh(8)
    step = _unwrap(make_sharded_step(integ, mesh))
    state = place_state(state0, integ.ins.grid, mesh)

    def chunk(st, dt):
        def body(s, _):
            return step(s, dt), ()
        out, _ = jax.lax.scan(body, st, None, length=2)
        return out

    return chunk, (state, _DT), ()


def _build_fftpar_transpose():
    # the pencil-FFT Helmholtz solve in isolation: on the (4, 2) mesh
    # over the 16^3 grid this is exactly 4 all_to_all transposes in,
    # 4 back out — the framework's true long-range communication
    import jax

    from ibamr_tpu.parallel import make_mesh
    from ibamr_tpu.parallel.fftpar import PencilFFT

    _require_devices(jax)
    integ, state = _shell()
    mesh = make_mesh(8)
    pencil = PencilFFT(integ.ins.grid, mesh)
    rhs = state.ins.u[0]
    return (lambda r: pencil.helmholtz(r, 200.0, -0.025)), (rhs,), ()


def _build_lagrangian_exchange():
    # the S2 co-partition exchange in isolation: owner bucketing +
    # local spread + ppermute halo accumulate (parallel/lagrangian);
    # ppermute count/bytes per sharded axis are the budget
    import jax
    import jax.numpy as jnp

    from ibamr_tpu.parallel import ShardedInteraction, make_mesh

    _require_devices(jax)
    integ, state = _shell()
    mesh = make_mesh(8)
    si = ShardedInteraction(integ.ins.grid, mesh,
                            n_markers=state.X.shape[0])
    F = jnp.zeros_like(state.X)

    def exchange(Fa, Xa, m):
        b = si.buckets(Xa, m)
        return si.spread_vel(Fa, Xa, weights=m, b=b)

    return exchange, (F, state.X, state.mask), ()


def _build_fleet_mesh_chunk():
    # the pod fleet's unit of work (PR 16): the 8-lane fleet chunk with
    # its lane axis sharded over the 8-device lane mesh (B×D — each
    # device owns whole lanes). Lanes are independent, so the ONLY
    # collectives the partitioner may insert are boundary reshard pins;
    # the budget holds this at zero-traffic and keeps the per-lane
    # freeze/dt structure identical to fleet_chunk.
    import jax
    import jax.numpy as jnp

    from ibamr_tpu.parallel.mesh import make_lane_mesh, place_lanes
    from ibamr_tpu.utils import lanes as _lanes

    _require_devices(jax)
    integ, state = _shell()
    mesh = make_lane_mesh(8)
    drv = _driver(integ, lanes=8, lane_mesh=mesh)
    chunk = _unwrap(drv._chunk(2))
    stacked = place_lanes(_lanes.stack_lanes([state] * 8), mesh)
    dt_vec = jnp.full((8,), _DT, dtype=jnp.float32)
    alive = jnp.ones((8,), dtype=bool)
    return chunk, (stacked, dt_vec, alive), ()


def _build_krylov_reduce():
    # the Krylov layer's per-iteration global reductions under GSPMD:
    # a sharded CG on the (shifted) periodic Poisson operator. On the
    # CPU mesh every global dot lowers to a synchronous all-reduce, so
    # ``collective_sync_ops`` counts the syncs per compiled module —
    # PR 16's fused ``tree_dots`` turns the two scalar (r,z)/(r,r)
    # reductions per iteration into ONE (2,)-vector reduction and the
    # budget pins the lower count.
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec

    from ibamr_tpu.parallel import make_mesh
    from ibamr_tpu.solvers.krylov import cg

    _require_devices(jax)
    mesh = make_mesh(8)
    sh = NamedSharding(mesh, PartitionSpec(*mesh.axis_names))

    def A(x):
        x = jax.lax.with_sharding_constraint(x, sh)
        return (7.0 * x
                - jnp.roll(x, 1, 0) - jnp.roll(x, -1, 0)
                - jnp.roll(x, 1, 1) - jnp.roll(x, -1, 1)
                - jnp.roll(x, 1, 2) - jnp.roll(x, -1, 2))

    b = jax.device_put(jnp.ones((_N, _N, _N), jnp.float32), sh)
    return (lambda r: cg(A, r, maxiter=8).x), (b,), ()


def _build_solo_step_256():
    from ibamr_tpu.models.shell3d import build_shell_example

    integ, state = build_shell_example(
        n_cells=256, n_lat=316, n_lon=316, radius=0.25, aspect=1.2,
        stiffness=1.0, rest_length_factor=0.75, mu=0.05,
        use_fast_interaction="packed")
    return (lambda s: integ.step(s, _DT)), (state,), ()


def _build_assim_analysis():
    # the masked B-lane ESRF analysis step (PR 20): instrument-panel
    # observation operator vmapped over lanes + ensemble-space
    # square-root update + pack/unpack of the assimilated state
    # subset. The args carry ONE QUARANTINED LANE and one rejected
    # channel on purpose — quarantine and QC act through mask VALUES,
    # so this is the trace signature the whole failure surface rides.
    # Pins: zero in-scan host transfers, zero scatters (gather-only
    # interp + dense (B,B) algebra), zero f64 widenings.
    import jax
    import jax.numpy as jnp

    from ibamr_tpu.assim import (ObservationOperator, esrf_analysis,
                                 state_packer)
    from ibamr_tpu.instruments import InstrumentPanel, make_meters
    from ibamr_tpu.utils import lanes as _lanes

    integ, state = _shell()
    loops = [[2 * _N_LON + j for j in range(_N_LON)],
             [5 * _N_LON + j for j in range(_N_LON)]]
    panel = InstrumentPanel(integ.ins.grid,
                            make_meters(loops, closed=True))
    op = ObservationOperator(panel)
    B = 4
    stacked = _lanes.broadcast_lane(state, B)
    pack, unpack, _n = state_packer(state)

    def analyze(fleet, y, r, om, alive, lam):
        ens = jax.vmap(pack)(fleet)
        obs_ens = jax.vmap(op)(fleet)
        ana, diag = esrf_analysis(ens, obs_ens, y, r, alive, om, lam)
        return jax.vmap(unpack)(fleet, ana), diag

    m = op.n_obs
    y = jnp.zeros((m,), jnp.float32)
    r = jnp.full((m,), 1e-4, jnp.float32)
    om = jnp.array([True] * (m - 1) + [False])      # one QC reject
    alive = jnp.array([True] * (B - 1) + [False])   # one quarantined
    lam = jnp.asarray(1.0, jnp.float32)
    return analyze, (stacked, y, r, om, alive, lam), ()


@dataclass(frozen=True)
class Artifact:
    """One named compiled artifact under contract."""
    name: str
    build: Callable[[], Tuple]        # () -> (fn, args, donate_argnums)
    heavy: bool = False               # flagship-scale: slow-tier / --heavy
    notes: str = ""


ARTIFACTS: Dict[str, Artifact] = {
    a.name: a for a in (
        Artifact("solo_step", _build_solo_step,
                 notes="full coupled IB step, packed engine, f32"),
        Artifact("solo_step_bf16",
                 lambda: _build_solo_step(spectral_dtype="bf16"),
                 notes="full step with bf16 spectral transforms"),
        Artifact("fused_substep", _build_fused_substep,
                 notes="k-space-resident Helmholtz+projection substep "
                       "(<= 2 batched FFTs is the fusion pin)"),
        Artifact("fused_substep_bf16",
                 lambda: _build_fused_substep(spectral_dtype="bf16"),
                 notes="mixed-precision substep; bf16 rounding converts "
                       "are budgeted, widenings are not"),
        Artifact("spread_packed",
                 lambda: _build_transfer("packed", "spread"),
                 notes="occupancy-packed force spread (zero scatters)"),
        Artifact("interp_packed",
                 lambda: _build_transfer("packed", "interp"),
                 notes="occupancy-packed velocity interp"),
        Artifact("spread_mxu",
                 lambda: _build_transfer(True, "spread"),
                 notes="dense one-hot MXU spread (zero scatters)"),
        Artifact("interp_mxu",
                 lambda: _build_transfer(True, "interp"),
                 notes="dense one-hot MXU interp"),
        Artifact("grad_substep", _build_grad_substep,
                 notes="full vjp round trip of the fused substep: the "
                       "cotangent rides the SAME plan, <= 2x primal "
                       "batched FFTs (fft_ops 4 vs 2) is the headline "
                       "adjoint-at-primal-cost pin"),
        Artifact("grad_spread",
                 lambda: _build_grad_transfer("spread"),
                 notes="packed spread backward pass: an interp through "
                       "the SAME buckets — zero scatter prims, zero "
                       "bucket preps"),
        Artifact("grad_interp",
                 lambda: _build_grad_transfer("interp"),
                 notes="packed interp backward pass: d/df reuses the "
                       "primal spread's scatter set (no new shapes), "
                       "d/dX the oracle weight-derivative pullback"),
        Artifact("grad_chunk", _build_grad_chunk,
                 notes="reverse mode through the remat-checkpointed "
                       "driver chunk; cotangent scan stays device-"
                       "resident (zero in-scan transfers) and dtype-"
                       "clean (zero f64 widenings)"),
        Artifact("solo_chunk", _build_solo_chunk,
                 notes="driver scan chunk + fused health probe; "
                       "host_transfers_in_scan == 0 is the pin"),
        Artifact("donated_chunk", _build_donated_chunk,
                 notes="cfg.donate=True chunk; donated_args >= 1 "
                       "verifies whole-chunk buffer donation"),
        Artifact("fleet_chunk", _build_fleet_chunk,
                 notes="2-lane vmapped chunk with lane-freeze select"),
        Artifact("solo_chunk_telemetry", _build_solo_chunk_telemetry,
                 notes="solo chunk lowered with a live run ledger, "
                       "driver span and per-chunk flush attached; "
                       "telemetry must stay host-side (same ceilings "
                       "as solo_chunk, zero in-scan transfers)"),
        Artifact("fleet_chunk_telemetry", _build_fleet_chunk_telemetry,
                 notes="fleet chunk lowered telemetry-on; same "
                       "ceilings as fleet_chunk, zero in-scan "
                       "transfers"),
        Artifact("donated_step", _build_donated_step,
                 notes="integrator jitted_step(donate=True); verified "
                       "against the compiled alias table"),
        Artifact("lane_fetch", _build_lane_fetch,
                 notes="per-lane capsule fetch (lane_slice) — zero "
                       "scatter/fft/host budget"),
        Artifact("served_chunk", _build_served_chunk,
                 notes="warm-pool 1-step ack chunk, 1 live + 1 padded "
                       "lane; the serving path pins the same in-scan "
                       "ceilings as the batch fleet chunk"),
        Artifact("open_channel_step", _build_open_channel_step,
                 notes="open-boundary stabilized-PPM step (saddle "
                       "Stokes); dtype-clean pin after the f64 "
                       "stab-mask finding"),
        Artifact("solo_step_256", _build_solo_step_256, heavy=True,
                 notes="flagship 256^3 coupled step (slow tier; "
                       "graph_audit --heavy)"),
        Artifact("sharded_chunk", _build_sharded_chunk,
                 notes="8-device sharded coupled IB chunk (pencil FFT "
                       "+ S2 transfers); the collective/overlap census "
                       "is the pod comm-layer pin"),
        Artifact("fftpar_transpose", _build_fftpar_transpose,
                 notes="pencil-FFT Helmholtz on the (4,2) mesh; "
                       "all_to_all transpose count/bytes budgeted"),
        Artifact("lagrangian_exchange", _build_lagrangian_exchange,
                 notes="S2 owner-bucketed spread with ppermute halo "
                       "accumulate; ppermute count/bytes budgeted"),
        Artifact("fleet_mesh_chunk", _build_fleet_mesh_chunk,
                 notes="8-lane fleet chunk sharded over the 8-device "
                       "lane mesh (B x D pod fleet); lanes are "
                       "independent so collective traffic stays zero"),
        Artifact("assim_analysis", _build_assim_analysis,
                 notes="masked B-lane ESRF analysis between scan "
                       "chunks (PR 20): instrument-panel obs operator "
                       "+ ensemble-space square-root update, one "
                       "quarantined lane and one rejected channel in "
                       "the trace — gather-only, dtype-clean, zero "
                       "host transfers"),
        Artifact("krylov_reduce", _build_krylov_reduce,
                 notes="sharded CG global reductions; fused tree_dots "
                       "pins one all-reduce sync per iteration pair"),
    )
}


def measure_artifact(name: str) -> dict:
    """Build + census one artifact under x64-off (production mode).

    Returns the flat budget-comparable metric dict. Caller chooses the
    backend; the CI gate runs this in a ``JAX_PLATFORMS=cpu`` child."""
    from jax.experimental import disable_x64

    from ibamr_tpu import obs

    art = ARTIFACTS[name]
    prev = obs.current()
    try:
        with disable_x64():
            fn, args, donate = art.build()
            census = graph_census(fn, args, donate_argnums=donate)
    finally:
        # telemetry-on builders attach a contract ledger that must stay
        # live through the census; restore whatever the CALLER had
        # attached (in-process test measurement must not steal a real
        # run's ledger)
        led = obs.current()
        if led is not prev:
            obs.detach()
            try:
                led.close()
            except Exception:
                pass
            if prev is not None:
                obs.attach(prev)
    return budget_metrics(census)


# ---------------------------------------------------------------------------
# budget load / diff
# ---------------------------------------------------------------------------

def load_budgets(path: Optional[str] = None) -> dict:
    with open(path or BUDGET_PATH) as f:
        doc = json.load(f)
    return doc.get("artifacts", {})


@dataclass
class Drift:
    """Per-artifact diff of measured metrics against the budget."""
    name: str
    regressions: Dict[str, Tuple[int, int]] = field(default_factory=dict)
    improvements: Dict[str, Tuple[int, int]] = field(default_factory=dict)
    missing: Tuple[str, ...] = ()     # budgeted metric absent in census

    @property
    def clean(self) -> bool:
        return not (self.regressions or self.improvements or self.missing)


def diff_budget(name: str, measured: dict, budget: dict) -> Drift:
    """Compare one artifact's measured metrics to its budget.

    Max metrics regress UP (measured > budget) and improve DOWN; the
    min metrics (``donated_args``) regress DOWN — a refactor that
    silently drops donation is a regression even though every other
    counter stays flat."""
    d = Drift(name)
    missing = []
    for metric, bound in budget.items():
        if metric not in measured:
            missing.append(metric)
            continue
        got = int(measured[metric])
        bound = int(bound)
        if metric in BUDGET_MIN_METRICS:
            if got < bound:
                d.regressions[metric] = (got, bound)
            elif got > bound:
                d.improvements[metric] = (got, bound)
        elif metric in BUDGET_MAX_METRICS:
            if got > bound:
                d.regressions[metric] = (got, bound)
            elif got < bound:
                d.improvements[metric] = (got, bound)
        # unknown metrics in the budget file are a budget-file bug:
        # surface as missing rather than silently passing
        else:
            missing.append(metric)
    d.missing = tuple(missing)
    return d


def report_drift(drifts) -> str:
    """Human-readable drift report (one block per non-clean artifact)."""
    lines = []
    for d in drifts:
        if d.clean:
            continue
        lines.append(f"[{d.name}]")
        for m, (got, bound) in sorted(d.regressions.items()):
            word = ("dropped below floor"
                    if m in BUDGET_MIN_METRICS else "exceeds budget")
            lines.append(f"  REGRESSED  {m}: {got} {word} {bound}")
        for m, (got, bound) in sorted(d.improvements.items()):
            lines.append(
                f"  improved   {m}: {got} (budget {bound}) — run "
                f"tools/graph_audit.py --tighten to ratchet")
        for m in d.missing:
            lines.append(f"  MISSING    {m}: not measurable / unknown "
                         f"metric — budget file and census disagree")
    return "\n".join(lines)
