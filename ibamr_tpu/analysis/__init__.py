"""Graph contracts: static analysis of the compiled artifacts (PR 8).

Every performance and robustness property the TPU hot path depends on —
zero scatters in the force assembly, two batched FFTs in the fused
spectral substep, no host transfers inside the scan, donation actually
honored by the compiled module, no silent dtype widenings — is a
*global invariant of the compiled graph*, not of any one source file.
This package audits the graphs themselves:

- :mod:`~ibamr_tpu.analysis.graph_census` — pure census functions over
  a traced jaxpr / compiled HLO module (op classes, FFT/dot traffic,
  dtype-promotion census, host-transfer census, donation audit);
- :mod:`~ibamr_tpu.analysis.contracts` — the registry of named
  hot-path artifacts and their budgets (``GRAPH_BUDGETS.json``),
  consumed by ``tools/graph_audit.py`` (the CI drift gate) and
  ``tests/test_graph_contracts.py`` (the tier-1 pin);
- :mod:`~ibamr_tpu.analysis.jit_lint` — an AST-level linter for
  jit-unsafety in the source itself (traced branches, host casts on
  tracers, wall-clock/RNG capture, mutable defaults), with an inline
  ``# jitlint: ok(<rule>): <why>`` waiver syntax.

See docs/ANALYSIS.md for the contract inventory and the budget-update
workflow.
"""
