"""Instrument panels: flow meters and pressure gauges on fiber loops.

Reference parity: ``IBInstrumentPanel`` + ``IBInstrumentationSpec``
(P13, SURVEY.md §2.2/§5.5) — meters defined by ordered marker loops
riding on the structure; each step they report the volumetric flux
through the surface spanned by the loop and the mean pressure along it,
appended to the metrics stream.

TPU-first redesign: the reference reduces per-rank partial sums over a
``ParallelMap`` (T14); here each meter is a static padded index array
and the readings are pure jitted reductions (interp gathers +
``segment_sum``), so instrumentation adds no host synchronization.

Geometry: the spanning surface is the centroid fan of the loop (exact
for planar loops, the reference's assumption as well):
  3D: flux = sum_tri u(centroid_tri) . n_tri A_tri
  2D: a "loop" is an open curve; flux = integral of u . n ds across it
      (n = left-normal of each segment).
"""

from __future__ import annotations

from typing import Dict, List, NamedTuple, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from ibamr_tpu.grid import StaggeredGrid
from ibamr_tpu.ops import interaction
from ibamr_tpu.ops.delta import Kernel

Vel = Tuple[jnp.ndarray, ...]


class MeterSpecs(NamedTuple):
    """B meters, each a padded chain of marker indices.

    idx: (B, L) int32 marker indices (pad slots repeat the first node);
    valid: (B, L) 0/1 — 1 for real nodes (pressure averaging);
    seg: (B, L) 0/1 — 1 for real segments k -> k+1 (flux); for closed
    meters this includes the closing segment back to the first node.
    """
    idx: jnp.ndarray
    valid: jnp.ndarray
    seg: jnp.ndarray


def make_meters(loops: Sequence[Sequence[int]], closed,
                dtype=jnp.float32) -> MeterSpecs:
    """Build padded meter specs from per-meter marker index lists.

    ``closed`` (required): bool or per-meter list — closed loops (3D
    spanning surfaces) include the closing segment; open chains (2D
    cross-section meters) must NOT (a closed 2D contour integral of u.n
    is ~0 for any near-div-free field, silently reading nothing).
    """
    B = len(loops)
    if isinstance(closed, bool):
        closed = [closed] * B
    L = max(len(l) for l in loops) + 1   # always >= 1 pad slot
    idx = np.zeros((B, L), dtype=np.int32)
    valid = np.zeros((B, L), dtype=np.float64)
    seg = np.zeros((B, L), dtype=np.float64)
    for b, loop in enumerate(loops):
        n = len(loop)
        idx[b, :n] = loop
        idx[b, n:] = loop[0]     # pad at the first node's position
        valid[b, :n] = 1.0
        seg[b, :n - 1] = 1.0
        if closed[b]:
            # segment n-1 -> n lands on the first node: the closer
            seg[b, n - 1] = 1.0
    return MeterSpecs(idx=jnp.asarray(idx),
                      valid=jnp.asarray(valid, dtype=dtype),
                      seg=jnp.asarray(seg, dtype=dtype))


class InstrumentPanel:
    """Flow-meter + pressure-gauge readings for marker loops (P13)."""

    def __init__(self, grid: StaggeredGrid, meters: MeterSpecs,
                 kernel: Kernel = "IB_4"):
        self.grid = grid
        self.meters = meters
        self.kernel = kernel

    # -- readings (pure, jittable) -------------------------------------------
    def readings(self, u: Vel, p: jnp.ndarray,
                 X: jnp.ndarray) -> Dict[str, jnp.ndarray]:
        """{"flux": (B,), "mean_pressure": (B,)}; one interp gather per
        quantity, reductions on device."""
        grid = self.grid
        idx, valid = self.meters.idx, self.meters.valid
        seg_valid = self.meters.seg
        B, L = idx.shape
        Xl = X[idx]                                     # (B, L, dim)

        if grid.dim == 2:
            # open-curve meter: segments between consecutive real nodes
            a = Xl
            b = jnp.roll(Xl, -1, axis=1)
            mid = 0.5 * (a + b).reshape(-1, 2)
            t = (b - a)
            # left normal (ds-weighted): (t_y, -t_x)
            nrm = jnp.stack([t[..., 1], -t[..., 0]], axis=-1).reshape(-1, 2)
            Um = interaction.interpolate_vel(u, grid, mid,
                                             kernel=self.kernel)
            flux = jnp.sum((Um * nrm).sum(-1).reshape(B, L)
                           * seg_valid, axis=1)
        else:
            # centroid-fan triangulation of each closed loop
            cnt = jnp.maximum(jnp.sum(valid, axis=1, keepdims=True), 1.0)
            cent = jnp.sum(Xl * valid[..., None], axis=1) / cnt   # (B, 3)
            a = Xl
            b = jnp.roll(Xl, -1, axis=1)
            tri_c = (a + b + cent[:, None, :]) / 3.0
            # area-weighted normal of triangle (cent, a, b)
            nrm = 0.5 * jnp.cross(a - cent[:, None, :],
                                  b - cent[:, None, :])
            Um = interaction.interpolate_vel(u, grid, tri_c.reshape(-1, 3),
                                             kernel=self.kernel)
            flux = jnp.sum((Um.reshape(B, L, 3) * nrm).sum(-1)
                           * seg_valid, axis=1)

        Pm = interaction.interpolate(p, grid, Xl.reshape(-1, grid.dim),
                                     centering="cell", kernel=self.kernel)
        cnt = jnp.maximum(jnp.sum(valid, axis=1), 1.0)
        mean_p = jnp.sum(Pm.reshape(B, L) * valid, axis=1) / cnt
        return {"flux": flux, "mean_pressure": mean_p}


class HydrodynamicForceEvaluator:
    """Control-volume drag/lift on an immersed body: the
    ``IBHydrodynamicForceEvaluator`` analog (SURVEY.md §5.5 [vintage]).

    The force the fluid exerts on whatever sits inside an axis-aligned
    control volume follows from the momentum balance over the CV:

      F_body = oint [ -rho u (u.n) - p n + mu (grad u + grad u^T).n ] dA
               - d/dt int_cv rho u dV

    ``surface_force`` evaluates the surface integral with second-order
    MAC quadrature (face-plane cell-center points; one-cell centered
    differences for the tractions); ``momentum`` returns the CV
    momentum integral so the caller can difference it across steps.
    All terms are pure jitted reductions — no host synchronization,
    like the meter readings above.

    The CV must not touch the domain boundary (one-cell clearance for
    the centered differences) and is defined on the PERIODIC lower-face
    MAC layout of :mod:`ibamr_tpu.integrators.ins`.
    """

    def __init__(self, grid: StaggeredGrid, lo: Sequence[int],
                 hi: Sequence[int], rho: float = 1.0, mu: float = 0.01):
        dim = grid.dim
        assert len(lo) == len(hi) == dim
        for d in range(dim):
            assert 1 <= lo[d] < hi[d] <= grid.n[d] - 1, \
                "CV needs one-cell clearance from the domain edge"
        self.grid = grid
        self.lo = tuple(int(v) for v in lo)
        self.hi = tuple(int(v) for v in hi)
        self.rho = float(rho)
        self.mu = float(mu)

    # -- helpers ---------------------------------------------------------
    def _box(self, a: jnp.ndarray) -> jnp.ndarray:
        return a[tuple(slice(l, h) for l, h in zip(self.lo, self.hi))]

    def _face_plane(self, a: jnp.ndarray, axis: int,
                    face: int) -> jnp.ndarray:
        """Slice ``a`` at index ``face`` along ``axis`` and to the CV
        cross-section in every other axis. ``face`` wraps (the layout
        is periodic), so the +-1 stencil offsets stay legal for a CV
        reaching to the last interior face."""
        sl = [slice(l, h) for l, h in zip(self.lo, self.hi)]
        sl[axis] = face % a.shape[axis]
        return a[tuple(sl)]

    # -- integrals -------------------------------------------------------
    def momentum(self, u: Vel) -> jnp.ndarray:
        """(dim,) rho * int_cv u dV (faces averaged to cell centers)."""
        import math

        dV = math.prod(self.grid.dx)
        out = []
        for d in range(self.grid.dim):
            cc = 0.5 * (u[d] + jnp.roll(u[d], -1, d))
            out.append(self.rho * jnp.sum(self._box(cc)) * dV)
        return jnp.stack(out)

    def surface_force(self, u: Vel, p: jnp.ndarray) -> jnp.ndarray:
        """(dim,) surface integral of the momentum flux + traction."""
        import math

        grid = self.grid
        dim = grid.dim
        dx = grid.dx
        rho, mu = self.rho, self.mu
        F = [jnp.zeros(()) for _ in range(dim)]
        for a in range(dim):
            dA = math.prod(dx[e] for e in range(dim) if e != a)
            for side, f in ((-1.0, self.lo[a]), (1.0, self.hi[a])):
                # u_a lives exactly on the face plane at cross-section
                # cell centers
                ua = self._face_plane(u[a], a, f)
                # p at the face: average of the two adjacent cells
                pf = 0.5 * (self._face_plane(p, a, f - 1)
                            + self._face_plane(p, a, f))
                # d u_a / d x_a at the face (centered over 2 dx)
                dua_da = (self._face_plane(u[a], a, f + 1)
                          - self._face_plane(u[a], a, f - 1)) \
                    / (2.0 * dx[a])
                # component a: -rho ua^2 n - p n + 2 mu dua/da n
                F[a] = F[a] + side * dA * jnp.sum(
                    -rho * ua * ua - pf + 2.0 * mu * dua_da)
                for d in range(dim):
                    if d == a:
                        continue
                    # u_d averaged to the same face points: faces ->
                    # centers along d, cells -> face plane along a
                    ud_cc = 0.5 * (u[d] + jnp.roll(u[d], -1, d))
                    ud = 0.5 * (self._face_plane(ud_cc, a, f - 1)
                                + self._face_plane(ud_cc, a, f))
                    dud_da = (self._face_plane(ud_cc, a, f)
                              - self._face_plane(ud_cc, a, f - 1)) \
                        / dx[a]
                    # d u_a / d x_d at the face points (centered along
                    # the transverse axis of the face-plane slice)
                    ua_full = jnp.take(u[a], f, axis=a)
                    dp = d - (1 if d > a else 0)   # axis d in the slice
                    dua_dd_full = (jnp.roll(ua_full, -1, dp)
                                   - jnp.roll(ua_full, 1, dp)) \
                        / (2.0 * dx[d])
                    sl = tuple(slice(self.lo[e], self.hi[e])
                               for e in range(dim) if e != a)
                    dua_dd = dua_dd_full[sl]
                    F[d] = F[d] + side * dA * jnp.sum(
                        -rho * ud * ua + mu * (dud_da + dua_dd))
        return jnp.stack(F)

    def body_force(self, u: Vel, p: jnp.ndarray, mom_prev: jnp.ndarray,
                   mom_new: jnp.ndarray, dt: float) -> jnp.ndarray:
        """F on the body: surface integral minus the CV momentum rate
        (``mom_*`` from :meth:`momentum` at consecutive steps; evaluate
        ``surface_force`` near the midpoint for second order)."""
        return self.surface_force(u, p) - (mom_new - mom_prev) / dt
