"""ibamr_tpu — a TPU-native immersed-boundary / incompressible-flow framework.

A from-scratch JAX/XLA/Pallas rebuild of the capabilities of the reference
C++/Fortran/MPI framework (huahbo/IBAMR): immersed-boundary fluid-structure
interaction on staggered Cartesian grids, designed TPU-first:

- Static-shape functional state pytrees; one jitted ``step: State -> State``.
- Staggered (MAC) grid vector calculus as fused XLA stencils (jnp.roll),
  which the SPMD partitioner lowers to halo exchanges over ICI when sharded.
- FFT-based Poisson/Helmholtz solves for the periodic acceptance configs;
  matrix-free Krylov (CG/GMRES) for everything else.
- Lagrangian markers as fixed-capacity structure-of-arrays; spread/interp
  with regularized delta kernels as vmapped gather/scatter.
- Multi-device scaling via ``jax.sharding.Mesh`` + ``NamedSharding``; no MPI.

Reference parity map (SURVEY.md section numbers):
  utils.input_db      <- SAMRAI tbox::Database input parser        [SURVEY §5.6]
  utils.gridfunctions <- muParserCartGridFunction (T12)            [SURVEY §2.1]
  utils.timers        <- TimerManager / IBTK_TIMER macros (§5.1)
  utils.checkpoint    <- RestartManager (§5.4)
  grid, ops.stencils  <- SAMRAI patch data + HierarchyMathOps (T4)
  solvers             <- IBTK solver infra (T6-T8) + StaggeredStokes (P3)
  ops.delta, ops.interaction <- LEInteractor (T2), LDataManager (T1)
  ops.forces, io.structures  <- IBStandardForceGen (P11), IBStandardInitializer (P10)
  integrators         <- HierarchyIntegrator (T13), INSStaggered (P2),
                         IBExplicit (P8), IBMethod (P9), AdvDiff (P19)
  parallel            <- SAMRAI load balancer / schedules as shardings (§2.4)
"""

__version__ = "0.1.0"
