"""3D elastic shell (the ex4-equivalent acceptance config).

Reference parity: ``examples/IB/explicit/ex4`` — a closed elastic shell
(pressurized/stretched spherical membrane discretized as a structured
marker lattice with spring + optional bending forces) immersed in a 3D
periodic incompressible fluid, IB_4 delta (BASELINE.json configs[1], the
north-star benchmark geometry: 128^3-256^3 grid, ~1e5 markers).

The shell is a latitude-longitude lattice: ``n_lat`` rings of ``n_lon``
markers each (poles excluded so every marker has full ring connectivity).
Springs run along rings (periodic) and along meridians (open chains);
``aspect`` != 1 starts the shell as a spheroid so taut springs drive a
relaxation flow — the 3D analog of the 2D ellipse-membrane test, with the
enclosed volume conserved by incompressibility.
"""

from __future__ import annotations

import math
from typing import Optional, Tuple

import numpy as np

from ibamr_tpu.grid import StaggeredGrid
from ibamr_tpu.integrators.ib import IBExplicitIntegrator, IBMethod, IBState
from ibamr_tpu.integrators.ins import INSStaggeredIntegrator
from ibamr_tpu.io.structures import StructureData


def make_spherical_shell(n_lat: int, n_lon: int, radius: float,
                         center: Tuple[float, float, float],
                         stiffness: float,
                         rest_length_factor: float = 1.0,
                         aspect: float = 1.0,
                         bend_rigidity: float = 0.0) -> StructureData:
    """Structured spherical-shell marker lattice with ring + meridian
    springs (and optional meridian beams). ``aspect`` stretches the z axis
    (prolate for aspect > 1). Marker (i, j) = ring i, longitude j; index
    = i * n_lon + j."""
    # exclude poles: theta in (0, pi)
    theta = math.pi * (np.arange(n_lat) + 0.5) / n_lat        # (n_lat,)
    phi = 2.0 * math.pi * np.arange(n_lon) / n_lon            # (n_lon,)
    st, ct = np.sin(theta)[:, None], np.cos(theta)[:, None]
    cp, sp = np.cos(phi)[None, :], np.sin(phi)[None, :]
    x = center[0] + radius * st * cp
    y = center[1] + radius * st * sp
    z = center[2] + radius * aspect * ct * np.ones_like(cp)
    verts = np.stack([x.ravel(), y.ravel(), z.ravel()], axis=1)

    def gid(i, j):
        return i * n_lon + j % n_lon

    I, J = np.meshgrid(np.arange(n_lat), np.arange(n_lon), indexing="ij")
    # ring springs: (i,j)-(i,j+1), rest length = local ring arc length
    ring0 = gid(I, J).ravel()
    ring1 = gid(I, J + 1).ravel()
    ring_rest = np.repeat(2.0 * math.pi * radius * np.sin(theta) / n_lon,
                          n_lon)
    # meridian springs: (i,j)-(i+1,j), i < n_lat-1
    Im, Jm = np.meshgrid(np.arange(n_lat - 1), np.arange(n_lon),
                         indexing="ij")
    mer0 = gid(Im, Jm).ravel()
    mer1 = gid(Im + 1, Jm).ravel()
    mer_rest = np.full(mer0.shape, math.pi * radius / n_lat)

    idx0 = np.concatenate([ring0, mer0])
    idx1 = np.concatenate([ring1, mer1])
    rest = np.concatenate([ring_rest, mer_rest]) * rest_length_factor
    springs = np.stack([idx0, idx1,
                        np.full(idx0.shape, stiffness), rest], axis=1)

    data = StructureData(name="shell3d", vertices=verts, springs=springs)
    if bend_rigidity > 0.0:
        # meridian bending triples (i-1, i, i+1) for interior rings
        Ib, Jb = np.meshgrid(np.arange(1, n_lat - 1), np.arange(n_lon),
                             indexing="ij")
        beams = np.stack([
            gid(Ib - 1, Jb).ravel(), gid(Ib, Jb).ravel(),
            gid(Ib + 1, Jb).ravel(),
            np.full(Ib.size, bend_rigidity)], axis=1)
        data.beams = beams
    return data


def shell_volume(X: np.ndarray, center: Tuple[float, float, float]):
    """Approximate enclosed volume via the divergence theorem over the
    marker cloud treated as radial samples: V ~ mean(r^3) * 4 pi / 3.
    Diagnostic only (exact volume conservation is checked in 2D)."""
    import jax.numpy as jnp
    c = jnp.asarray(center, dtype=X.dtype)
    r = jnp.sqrt(jnp.sum((X - c) ** 2, axis=-1))
    return (4.0 / 3.0) * math.pi * jnp.mean(r ** 3)


def construct_transfer_engine(name, grid: StaggeredGrid, vertices,
                              kernel: str):
    """Registry builder: construct the named transfer engine against
    ``grid`` for a structure with marker positions ``vertices``.
    ``name`` uses the ``use_fast_interaction`` vocabulary (True/False/
    str); "scatter" returns None (the IBMethod scatter/gather path).
    Raises on unsatisfiable geometry (e.g. packed3 with no valid z
    tile) — :func:`build_engine_with_fallback` turns such failures
    into degradation instead of death."""
    import jax.numpy as jnp

    from ibamr_tpu.ops.interaction_packed import normalize_engine_name

    name = normalize_engine_name(name)
    if name == "scatter":
        return None
    n_markers = vertices.shape[0]

    def bounded_cap():
        # pole-clustered tiles overflow into the compact scatter
        # path; keep the dense capacity bounded so padding FLOPs
        # stay sane. Only the bucketed (mxu/pallas) layouts use a
        # per-tile cap — the packed layouts size chunks instead.
        from ibamr_tpu.ops.interaction_fast import suggest_cap
        return min(suggest_cap(grid, vertices, kernel=kernel, tile=8,
                               slack=1.2),
                   1024)

    if name == "pallas":
        from ibamr_tpu.ops.pallas_interaction import PallasInteraction
        return PallasInteraction(
            grid, kernel=kernel, tile=8, cap=bounded_cap(),
            overflow_cap=max(2048, n_markers // 4))
    if name in ("packed3", "packed3_bf16"):
        from ibamr_tpu.ops.interaction_packed3 import (
            PackedInteraction3, suggest_chunks3)
        # z-tile: the largest of (16, 8) that divides the z extent
        # AND leaves room for the footprint (extent >= tz+s+1, s=4
        # for IB_4 — make_geometry3's own constraints)
        from ibamr_tpu.ops.delta import get_kernel as _gk
        _s = _gk(kernel)[0]
        n = grid.n
        tz = next((t for t in (16, 8)
                   if n[-1] % t == 0 and n[-1] >= t + _s + 1
                   and t >= _s + 1), None)
        if tz is None:
            raise ValueError(
                f"packed3 engine: no valid z tile for n_z = "
                f"{n[-1]} with kernel {kernel!r} (need n_z "
                f"divisible by 8 or 16 with n_z >= tile+"
                f"{_s + 1}); use the 'packed' engine instead")
        Q3 = suggest_chunks3(grid, vertices, kernel=kernel, tile=8,
                             tile_last=tz, chunk=64, slack=1.3)
        return PackedInteraction3(
            grid, kernel=kernel, tile=8, tile_last=tz, chunk=64,
            nchunks=Q3,
            overflow_cap=max(2048, n_markers // 4),
            compute_dtype=(jnp.bfloat16 if name == "packed3_bf16"
                           else None))
    if name in ("packed", "pallas_packed", "packed_bf16",
                "hybrid_packed", "hybrid_packed_bf16", "hybrid_bf16"):
        from ibamr_tpu.ops.interaction_packed import (
            PackedInteraction, suggest_chunks)
        Q = suggest_chunks(grid, vertices, kernel=kernel, tile=8,
                           chunk=128, slack=1.3)
        if name == "pallas_packed":
            from ibamr_tpu.ops.pallas_interaction import (
                PallasPackedInteraction)
            return PallasPackedInteraction(
                grid, kernel=kernel, tile=8, chunk=128, nchunks=Q,
                overflow_cap=max(2048, n_markers // 4))
        if name in ("hybrid_packed", "hybrid_packed_bf16",
                    "hybrid_bf16"):
            # "hybrid_bf16" is the canonical name of the
            # pallas-spread + XLA-bf16-interp composition
            # ("hybrid_packed_bf16" kept as an alias)
            from ibamr_tpu.ops.pallas_interaction import (
                HybridPackedInteraction)
            return HybridPackedInteraction(
                grid, kernel=kernel, tile=8, chunk=128, nchunks=Q,
                overflow_cap=max(2048, n_markers // 4),
                compute_dtype=(jnp.bfloat16
                               if name in ("hybrid_packed_bf16",
                                           "hybrid_bf16") else None))
        return PackedInteraction(
            grid, kernel=kernel, tile=8, chunk=128, nchunks=Q,
            overflow_cap=max(2048, n_markers // 4),
            compute_dtype=(jnp.bfloat16 if name == "packed_bf16"
                           else None))
    if name in ("mxu", "mxu_bf16"):
        from ibamr_tpu.ops.interaction_fast import FastInteraction
        return FastInteraction(
            grid, kernel=kernel, tile=8, cap=bounded_cap(),
            overflow_cap=max(2048, n_markers // 4),
            compute_dtype=(jnp.bfloat16 if name == "mxu_bf16"
                           else None))
    raise ValueError(f"unknown transfer engine {name!r}")


def probe_transfer_engine(fast, vertices) -> None:
    """Trace AND compile (without executing) a bucket + spread +
    interp composition at the real marker shapes — the cheap stand-in
    for 'does this engine's first step survive': trace-time failures
    (a monkeypatched or buggy engine method) and XLA/Mosaic compile
    failures (the round-2 Pallas remote-compile stall) both surface
    here, at build time, where degradation is still possible."""
    if fast is None:
        return
    import jax
    import jax.numpy as jnp

    X = jnp.asarray(vertices)
    F = jnp.zeros_like(X)

    def fn(F, X):
        b = fast.buckets(X)
        g = fast.spread_vel(F, X, b=b)
        return fast.interpolate_vel(g, X, b=b)

    jax.jit(fn).lower(F, X).compile()


# engines worth a build-time compile probe: the Pallas-backed family,
# whose compile path (Mosaic lowering, this container's remote-compile
# relay) has actually failed in the field (round 2). The plain-XLA
# engines skip the probe — construction errors still degrade, and
# probing them would tax every build for a failure mode never observed.
_PROBED_ENGINES = frozenset(
    {"pallas", "pallas_packed", "hybrid_packed", "hybrid_packed_bf16",
     "hybrid_bf16"})


def build_engine_with_fallback(name, grid: StaggeredGrid, vertices,
                               kernel: str, probe="auto"):
    """Construct ``name``'s transfer engine, degrading down the
    registry fallback chain (ops.interaction_packed.ENGINE_FALLBACKS)
    when construction or compile fails: each failure logs a warning
    naming the failed engine and its replacement, and the run
    continues on the next engine instead of dying. ``probe`` is True /
    False / "auto" (probe only the Pallas-backed engines). The
    terminal "scatter" link cannot fail (engine None). Returns
    ``(engine_or_None, engine_name)``."""
    import warnings

    from ibamr_tpu.ops.interaction_packed import fallback_chain

    chain = fallback_chain(name)
    for i, eng_name in enumerate(chain):
        try:
            fast = construct_transfer_engine(eng_name, grid, vertices,
                                             kernel)
            if probe is True or (probe == "auto"
                                 and eng_name in _PROBED_ENGINES):
                probe_transfer_engine(fast, vertices)
            return fast, eng_name
        except Exception as e:
            nxt = chain[i + 1]
            from ibamr_tpu.ops.interaction_packed import \
                record_engine_fallback
            record_engine_fallback(eng_name, nxt)
            warnings.warn(
                f"transfer engine {eng_name!r} failed to "
                f"build/compile ({type(e).__name__}: {e}); degrading "
                f"to {nxt!r}", RuntimeWarning)
    raise AssertionError("unreachable: scatter link cannot fail")


def build_shell_example(
        n_cells: int = 64,
        n_lat: int = 32,
        n_lon: int = 32,
        radius: float = 0.25,
        aspect: float = 1.2,
        stiffness: float = 1.0,
        rest_length_factor: float = 0.75,
        bend_rigidity: float = 0.0,
        rho: float = 1.0,
        mu: float = 0.05,
        kernel: str = "IB_4",
        convective_op_type: str = "centered",
        use_fast_interaction: Optional[bool] = None,
        dtype=None,
        input_db=None,
        engine_fallback: bool = True,
        spectral_dtype=None) -> Tuple[IBExplicitIntegrator,
                                      IBState]:
    """Assemble the ex4-equivalent simulation (3D periodic unit box).

    ``use_fast_interaction``: True = bucketed-MXU spread/interp engine
    (ops.interaction_fast); ``"packed"`` = the occupancy-packed chunk
    engine (ops.interaction_packed — best for surface structures whose
    tile occupancy is silhouette-clustered); ``"pallas"`` = the Pallas
    tile-kernel engine (ops.pallas_interaction); ``"pallas_packed"`` =
    occupancy-packed chunks driven by Pallas programs (no HBM weight
    intermediates); ``"mxu_bf16"`` / ``"packed_bf16"`` = the MXU /
    packed engines with bf16-compressed contraction operands (halves
    the dominant HBM traffic; ~3 decimal digits of delta-weight
    precision); False = XLA scatter/gather. None = auto, resolved by
    :mod:`ibamr_tpu.models.engine_resolver` (``IBAMR_TRANSFER_ENGINE``
    env override, ``IBAMR_TUNING_DB`` tuning file, else the built-in
    promotion: the occupancy-packed engine when the grid is
    tile-divisible and the marker count is large enough to matter,
    scatter otherwise). The resolved name lands on ``ib.engine_name``
    for fingerprinting/cache keying.

    ``engine_fallback`` (default True; knob ``IBMethod {
    engine_fallback = FALSE }``): when the chosen engine fails to
    build or compile, degrade down the registry fallback chain
    (docs/RESILIENCE.md) with a warning instead of raising.
    """
    import jax.numpy as jnp

    if dtype is None:
        dtype = jnp.float32

    n = (n_cells,) * 3
    x_lo, x_up = (0.0,) * 3, (1.0,) * 3
    if input_db is not None:
        geo = input_db.get_database_with_default("CartesianGeometry")
        n = tuple(int(v) for v in geo.get_int_array("n_cells", list(n)))
        x_lo = tuple(float(v) for v in geo.get_array("x_lo", list(x_lo)))
        x_up = tuple(float(v) for v in geo.get_array("x_up", list(x_up)))
        ins_db = input_db.get_database_with_default(
            "INSStaggeredHierarchyIntegrator")
        rho = ins_db.get_float("rho", rho)
        mu = ins_db.get_float("mu", mu)
        convective_op_type = ins_db.get_string("convective_op_type",
                                               convective_op_type)
        # spectral transform precision knob (reference-style):
        # INSStaggeredHierarchyIntegrator { spectral_dtype = "bf16" }
        # — bf16/split-real transform operands, f32 twiddle/
        # accumulation; "f32" (default) is the full-precision path
        spectral_dtype = ins_db.get_string(
            "spectral_dtype",
            spectral_dtype if spectral_dtype is not None else "f32")
        ib_db = input_db.get_database_with_default("IBMethod")
        kernel = ib_db.get_string("delta_fcn", kernel)
        # reference-style engine knob: IBMethod { transfer_engine =
        # "auto"|"scatter"|"mxu"|"packed"|"pallas"|"pallas_packed"|
        # "mxu_bf16"|"packed_bf16"|...|"hybrid_bf16" }
        if use_fast_interaction is None:
            _KNOB = ("auto", "scatter", "mxu", "packed", "pallas",
                     "pallas_packed", "mxu_bf16", "packed_bf16",
                     "packed3", "packed3_bf16", "hybrid_packed",
                     "hybrid_packed_bf16", "hybrid_bf16")
            eng = ib_db.get_string("transfer_engine", "auto").lower()
            if eng not in _KNOB:
                raise ValueError(
                    f"IBMethod.transfer_engine = {eng!r}: expected one "
                    f"of {_KNOB}")
            use_fast_interaction = {
                "auto": None, "scatter": False, "mxu": True,
            }.get(eng, eng)
        # IBMethod { engine_fallback = FALSE } pins the named engine:
        # a build/compile failure raises instead of degrading
        engine_fallback = ib_db.get_bool("engine_fallback",
                                         engine_fallback)
        sh = input_db.get_database_with_default("Shell")
        n_lat = sh.get_int("n_lat", n_lat)
        n_lon = sh.get_int("n_lon", n_lon)
        radius = sh.get_float("radius", radius)
        aspect = sh.get_float("aspect", aspect)
        stiffness = sh.get_float("stiffness", stiffness)
        rest_length_factor = sh.get_float("rest_length_factor",
                                          rest_length_factor)
        bend_rigidity = sh.get_float("bend_rigidity", bend_rigidity)

    grid = StaggeredGrid(n=n, x_lo=x_lo, x_up=x_up)
    ins = INSStaggeredIntegrator(grid, rho=rho, mu=mu,
                                 convective_op_type=convective_op_type,
                                 dtype=dtype,
                                 spectral_dtype=spectral_dtype)
    center = tuple(0.5 * (lo + hi) for lo, hi in zip(x_lo, x_up))
    structure = make_spherical_shell(
        n_lat, n_lon, radius, center=center,
        stiffness=stiffness, rest_length_factor=rest_length_factor,
        aspect=aspect, bend_rigidity=bend_rigidity)
    n_markers = structure.vertices.shape[0]
    from ibamr_tpu.ops.delta import get_kernel
    support, _ = get_kernel(kernel)
    if use_fast_interaction is None:
        # auto resolves through the pluggable resolver (env override,
        # tuning-DB file, else the built-in round-5 packed promotion)
        # so the flight-recorder fingerprint and the serving cache key
        # carry the RESOLVED engine, never the "auto" alias, and the
        # tune/ autotuner has a seam to publish winners into. The
        # spectral dtype is part of the query: the measured ranking can
        # differ between f32 and bf16 transform configurations.
        from ibamr_tpu.models.engine_resolver import resolve_engine
        resolved = resolve_engine(n, n_markers, support,
                                  spectral_dtype=spectral_dtype)
        use_fast_interaction = {
            "scatter": False, "mxu": True}.get(resolved, resolved)
    _ENGINES = (True, False, None, "pallas", "packed", "pallas_packed",
                "mxu_bf16", "packed_bf16", "packed3", "packed3_bf16",
                "hybrid_packed", "hybrid_packed_bf16", "hybrid_bf16")
    if use_fast_interaction not in _ENGINES:
        raise ValueError(
            f"unknown use_fast_interaction {use_fast_interaction!r}; "
            f"one of {_ENGINES}")
    if engine_fallback:
        fast, eng_name = build_engine_with_fallback(
            use_fast_interaction, grid, structure.vertices, kernel)
    else:
        from ibamr_tpu.ops.interaction_packed import normalize_engine_name
        fast = construct_transfer_engine(
            use_fast_interaction, grid, structure.vertices, kernel)
        eng_name = normalize_engine_name(use_fast_interaction)
    ib = IBMethod(structure.force_specs(dtype=dtype), kernel=kernel,
                  fast=fast)
    # the RESOLVED engine (post-auto-resolution, post-fallback): what
    # the flight-recorder fingerprint and the serving cache key carry
    ib.engine_name = eng_name
    integ = IBExplicitIntegrator(ins, ib, scheme="midpoint")
    state = integ.initialize(structure.vertices)
    return integ, state
