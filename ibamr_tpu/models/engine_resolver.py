"""Pluggable transfer-engine resolver — the autotuner seam.

``build_shell_example(use_fast_interaction=None)`` ("auto") used to
hard-code the round-5 packed promotion inline. The serving cache
(ibamr_tpu/serve/aot_cache.py) keys executables on the RESOLVED engine,
and the ROADMAP on-device autotuner needs a place to publish measured
winners — so auto resolution now routes through this module:

1. ``IBAMR_TRANSFER_ENGINE`` env var: an explicit operator override
   (validated against the engine vocabulary; ``"auto"``/empty defers).
2. ``IBAMR_TUNING_DB`` env var: path to a JSON tuning database — the
   autotuner's publication format. Entries match on grid shape and
   marker count; the first match wins::

       {"entries": [
         {"engine": "packed3", "n_cells": 256},
         {"engine": "packed", "markers_min": 4096}
       ]}

   Recognized match fields (all optional; an entry with none matches
   everything): ``n_cells`` (exact cubic extent), ``n`` (exact grid
   list), ``markers_min`` / ``markers_max`` (inclusive marker-count
   band).
3. The built-in heuristic: the round-5 promotion (occupancy-packed
   when the grid is tile-divisible and the marker count is large
   enough to matter; scatter otherwise).

The resolver returns a RESOLVED engine name — never ``"auto"`` — so the
flight-recorder fingerprint and the serving cache key always reflect
what actually runs. A bad override or a corrupt tuning DB raises at
build time (fail-fast: a typo'd engine name must die here, not silently
fall back and poison a cache key).
"""

from __future__ import annotations

import json
import os
from typing import Optional, Sequence

ENV_ENGINE = "IBAMR_TRANSFER_ENGINE"
ENV_TUNING_DB = "IBAMR_TUNING_DB"

# the resolved-name vocabulary (normalize_engine_name output space);
# "auto" is deliberately absent — resolution must terminate here
RESOLVED_ENGINES = (
    "scatter", "mxu", "packed", "pallas", "pallas_packed", "mxu_bf16",
    "packed_bf16", "packed3", "packed3_bf16", "hybrid_packed",
    "hybrid_packed_bf16", "hybrid_bf16")


def default_rule(n: Sequence[int], n_markers: int, support: int) -> str:
    """The built-in promotion: auto requires tile divisibility AND the
    make_geometry minimum extent (tile + support + 1) so small grids
    fall back to the scatter path instead of raising (ADVICE round 1).
    Round 5: auto picks the occupancy-PACKED engine — the on-chip
    shootout measured it 2.6x the bucketed-MXU engine at 256^3 (9.19
    vs 3.53 steps/s) and 4.2x at 128^3, roundoff-exact vs the scatter
    oracle (bf16 compression stays opt-in: exactness is the default
    contract)."""
    eligible = (
        n_markers >= 4096
        and all(v % 8 == 0 for v in n[:-1])
        and all(v >= 8 + support + 1 for v in n[:-1]))
    return "packed" if eligible else "scatter"


def _validate(name: str, source: str) -> str:
    if name not in RESOLVED_ENGINES:
        raise ValueError(
            f"{source}: unknown transfer engine {name!r}; expected one "
            f"of {RESOLVED_ENGINES}")
    return name


def load_tuning_db(path: str) -> list:
    """Entries of a tuning-DB file; raises on unreadable/malformed input
    (a configured-but-broken DB is an error, not a silent fallback)."""
    with open(path) as f:
        doc = json.load(f)
    entries = doc.get("entries")
    if not isinstance(entries, list):
        raise ValueError(
            f"tuning DB {path}: expected a top-level 'entries' list")
    return entries


def _entry_matches(entry: dict, n: Sequence[int], n_markers: int) -> bool:
    if "n_cells" in entry:
        if not all(int(v) == int(entry["n_cells"]) for v in n):
            return False
    if "n" in entry:
        if [int(v) for v in entry["n"]] != [int(v) for v in n]:
            return False
    if "markers_min" in entry and n_markers < int(entry["markers_min"]):
        return False
    if "markers_max" in entry and n_markers > int(entry["markers_max"]):
        return False
    return True


def resolve_engine(n: Sequence[int], n_markers: int, support: int,
                   env: Optional[dict] = None) -> str:
    """Resolve the ``auto`` engine alias to a concrete engine name for a
    grid of extents ``n`` carrying ``n_markers`` markers under a delta
    kernel of half-width ``support``. Resolution order: env override,
    tuning DB, built-in heuristic. ``env`` substitutes for
    ``os.environ`` in tests."""
    env = os.environ if env is None else env
    override = str(env.get(ENV_ENGINE, "") or "").strip().lower()
    if override and override != "auto":
        return _validate(override, f"${ENV_ENGINE}")
    db_path = str(env.get(ENV_TUNING_DB, "") or "").strip()
    if db_path:
        for entry in load_tuning_db(db_path):
            if _entry_matches(entry, n, n_markers):
                return _validate(str(entry.get("engine", "")).lower(),
                                 f"tuning DB {db_path}")
    return default_rule(n, n_markers, support)
