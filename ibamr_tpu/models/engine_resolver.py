"""Pluggable transfer-engine resolver — the autotuner's consumption seam.

``build_shell_example(use_fast_interaction=None)`` ("auto") used to
hard-code the round-5 packed promotion inline. The serving cache
(ibamr_tpu/serve/aot_cache.py) keys executables on the RESOLVED engine,
and the measured-search autotuner (ibamr_tpu/tune/, docs/TUNING.md)
publishes winners here — so auto resolution routes through this module:

1. ``IBAMR_TRANSFER_ENGINE`` env var: an explicit operator override
   (validated against the engine vocabulary; ``"auto"``/empty defers).
2. A JSON tuning database: ``IBAMR_TUNING_DB`` env var when set (the
   values ``none``/``off``/``0`` disable DB lookup entirely), else the
   committed ``TUNING_DB.json`` at the repo root when it exists.
   Schema v1 (``{"schema": 1, "entries": [...]}``; the legacy
   schema-less ``{"entries": [...]}`` form is still read). Entries
   match on grid shape, marker count, spectral dtype, platform and
   chunk length; the MOST SPECIFIC match wins, with file order as the
   deterministic tiebreak (earlier wins at equal specificity)::

       {"schema": 1, "entries": [
         {"engine": "packed_bf16", "n": [256, 256, 256],
          "platform": "tpu", "spectral_dtype": "f32",
          "provenance": {"platform": "tpu"}},
         {"engine": "packed", "markers_min": 4096}
       ]}

   Recognized match fields (all optional; an entry with none matches
   everything): ``n_cells`` (exact cubic extent), ``n`` (exact grid
   list), ``markers_min`` / ``markers_max`` (inclusive marker-count
   band), ``spectral_dtype`` (the fluid transform precision knob),
   ``platform`` (jax backend name), ``chunk_length`` (scan chunk
   length — only matched when the caller resolves for a specific
   length; a pinned field the query does not supply does NOT match).
   An entry whose ``provenance.platform`` differs from the current
   backend is SKIPPED silently — a CPU-measured winner can never steer
   a TPU run, and the committed TPU-measured defaults fall through to
   the heuristic on the CPU test backend.
3. The built-in heuristic: the round-5 promotion (occupancy-packed
   when the grid is tile-divisible and the marker count is large
   enough to matter; scatter otherwise).

The resolver returns a RESOLVED engine name — never ``"auto"`` — so the
flight-recorder fingerprint and the serving cache key always reflect
what actually runs. A bad override or a corrupt tuning DB raises at
build time (fail-fast: a typo'd engine name must die here, not silently
fall back and poison a cache key). DB consultations are counted on the
telemetry bus (``tuning_db_{hits,fallbacks,provenance_skips}_total``)
so `tools/obs.py summary` can report hit/fallback efficacy per run.
"""

from __future__ import annotations

import json
import os
from typing import Optional, Sequence

from ibamr_tpu import obs as _obs

ENV_ENGINE = "IBAMR_TRANSFER_ENGINE"
ENV_TUNING_DB = "IBAMR_TUNING_DB"

# IBAMR_TUNING_DB sentinel values that disable DB lookup (including
# the committed default DB)
DB_DISABLE_VALUES = ("none", "off", "0")

REPO_ROOT = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))
DEFAULT_DB_PATH = os.path.join(REPO_ROOT, "TUNING_DB.json")

DB_SCHEMA = 1

# the resolved-name vocabulary (normalize_engine_name output space);
# "auto" is deliberately absent — resolution must terminate here
RESOLVED_ENGINES = (
    "scatter", "mxu", "packed", "pallas", "pallas_packed", "mxu_bf16",
    "packed_bf16", "packed3", "packed3_bf16", "hybrid_packed",
    "hybrid_packed_bf16", "hybrid_bf16")

# match-field specificity weights: an exact grid list outranks a cubic
# extent; every other pinned field counts 1. The sum is the entry's
# specificity score; most-specific-match-wins with file order breaking
# ties (earlier wins) — deterministic, never first-match-in-file-order
# (overlapping entries used to silently shadow each other).
MATCH_FIELDS = ("n_cells", "n", "markers_min", "markers_max",
                "spectral_dtype", "platform", "chunk_length")
_FIELD_WEIGHT = {"n": 2}

_HITS = _obs.counter("tuning_db_hits_total")
_FALLBACKS = _obs.counter("tuning_db_fallbacks_total")
_PROV_SKIPS = _obs.counter("tuning_db_provenance_skips_total")


def default_rule(n: Sequence[int], n_markers: int, support: int) -> str:
    """The built-in promotion: auto requires tile divisibility AND the
    make_geometry minimum extent (tile + support + 1) so small grids
    fall back to the scatter path instead of raising (ADVICE round 1).
    Round 5: auto picks the occupancy-PACKED engine — the on-chip
    shootout measured it 2.6x the bucketed-MXU engine at 256^3 (9.19
    vs 3.53 steps/s) and 4.2x at 128^3, roundoff-exact vs the scatter
    oracle (bf16 compression stays opt-in: exactness is the default
    contract)."""
    eligible = (
        n_markers >= 4096
        and all(v % 8 == 0 for v in n[:-1])
        and all(v >= 8 + support + 1 for v in n[:-1]))
    return "packed" if eligible else "scatter"


def _validate(name: str, source: str) -> str:
    if name not in RESOLVED_ENGINES:
        raise ValueError(
            f"{source}: unknown transfer engine {name!r}; expected one "
            f"of {RESOLVED_ENGINES}")
    return name


def normalize_spectral_dtype(value) -> str:
    """Canonical spectral-dtype token for matching: ``None`` means the
    full-precision default ("f32")."""
    return str(value).strip().lower() if value else "f32"


# parsed-DB cache keyed on (path, mtime) — resolve_engine runs once per
# build, but the serving router builds many pools per process
_db_cache: dict = {}


def load_tuning_db(path: str) -> list:
    """Entries of a tuning-DB file; raises on unreadable/malformed input
    (a configured-but-broken DB is an error, not a silent fallback).
    Accepts schema v1 (``{"schema": 1, "entries": [...]}``) and the
    legacy schema-less form."""
    try:
        mtime = os.path.getmtime(path)
        cached = _db_cache.get(path)
        if cached is not None and cached[0] == mtime:
            return cached[1]
    except OSError:
        mtime = None
    with open(path) as f:
        doc = json.load(f)
    schema = doc.get("schema")
    if schema is not None and schema != DB_SCHEMA:
        raise ValueError(
            f"tuning DB {path}: unknown schema {schema!r} "
            f"(this build reads schema {DB_SCHEMA})")
    entries = doc.get("entries")
    if not isinstance(entries, list):
        raise ValueError(
            f"tuning DB {path}: expected a top-level 'entries' list")
    if mtime is not None:
        _db_cache[path] = (mtime, entries)
    return entries


def entry_specificity(entry: dict) -> int:
    """Specificity score: the weighted count of pinned match fields
    (``n`` counts double — an exact grid list is more specific than a
    cubic extent). Ties resolve to file order (earlier wins)."""
    return sum(_FIELD_WEIGHT.get(f, 1) for f in MATCH_FIELDS
               if entry.get(f) is not None)


def entry_matches(entry: dict, n: Sequence[int], n_markers: int,
                  spectral_dtype: Optional[str] = None,
                  platform: Optional[str] = None,
                  chunk_length: Optional[int] = None) -> bool:
    """Does ``entry`` match the query configuration? A pinned field the
    query does not supply (platform unknown, no chunk length) does NOT
    match — steering on unknown context would be a guess, and the
    heuristic is a better guess."""
    if entry.get("n_cells") is not None:
        if not all(int(v) == int(entry["n_cells"]) for v in n):
            return False
    if entry.get("n") is not None:
        if [int(v) for v in entry["n"]] != [int(v) for v in n]:
            return False
    if entry.get("markers_min") is not None \
            and n_markers < int(entry["markers_min"]):
        return False
    if entry.get("markers_max") is not None \
            and n_markers > int(entry["markers_max"]):
        return False
    if entry.get("spectral_dtype") is not None:
        if (normalize_spectral_dtype(entry["spectral_dtype"])
                != normalize_spectral_dtype(spectral_dtype)):
            return False
    if entry.get("platform") is not None:
        if platform is None \
                or str(entry["platform"]).lower() != str(platform).lower():
            return False
    if entry.get("chunk_length") is not None:
        if chunk_length is None \
                or int(entry["chunk_length"]) != int(chunk_length):
            return False
    return True


def provenance_compatible(entry: dict,
                          platform: Optional[str]) -> bool:
    """A ``provenance.platform`` pin restricts an entry to the backend
    it was measured on — a CPU-measured winner must never steer a TPU
    run (and vice versa). Unknown current platform fails closed."""
    prov = entry.get("provenance") or {}
    pinned = prov.get("platform")
    if pinned is None:
        return True
    return (platform is not None
            and str(pinned).lower() == str(platform).lower())


def current_platform() -> Optional[str]:
    """The active jax backend name, or None when jax is unavailable
    (entries pinning a platform then never match — fail closed)."""
    try:
        import jax
        return jax.default_backend()
    except Exception:
        return None


def lookup_tuning_db(entries: list, n: Sequence[int], n_markers: int,
                     spectral_dtype: Optional[str] = None,
                     platform: Optional[str] = None,
                     chunk_length: Optional[int] = None
                     ) -> Optional[dict]:
    """The winning DB entry for a query, or None. Most-specific-match
    wins; equal specificity resolves to file order (earlier wins).
    Provenance-incompatible entries are skipped (counted) before
    matching."""
    best, best_score = None, -1
    for entry in entries:
        if not isinstance(entry, dict):
            raise ValueError(f"tuning DB entry is not an object: "
                             f"{entry!r}")
        if not provenance_compatible(entry, platform):
            _PROV_SKIPS.inc()
            continue
        if not entry_matches(entry, n, n_markers,
                             spectral_dtype=spectral_dtype,
                             platform=platform,
                             chunk_length=chunk_length):
            continue
        score = entry_specificity(entry)
        if score > best_score:      # ties keep the EARLIER entry
            best, best_score = entry, score
    return best


def resolve_engine(n: Sequence[int], n_markers: int, support: int,
                   env: Optional[dict] = None, *,
                   spectral_dtype: Optional[str] = None,
                   platform: Optional[str] = None,
                   chunk_length: Optional[int] = None) -> str:
    """Resolve the ``auto`` engine alias to a concrete engine name for a
    grid of extents ``n`` carrying ``n_markers`` markers under a delta
    kernel of half-width ``support``. Resolution order: env override,
    tuning DB (most-specific match; see module docstring), built-in
    heuristic. ``env`` substitutes for ``os.environ`` in tests;
    ``platform`` defaults to the active jax backend."""
    env = os.environ if env is None else env
    override = str(env.get(ENV_ENGINE, "") or "").strip().lower()
    if override and override != "auto":
        return _validate(override, f"${ENV_ENGINE}")
    db_path = str(env.get(ENV_TUNING_DB, "") or "").strip()
    if db_path.lower() in DB_DISABLE_VALUES:
        return default_rule(n, n_markers, support)
    if not db_path and os.path.exists(DEFAULT_DB_PATH):
        db_path = DEFAULT_DB_PATH
    if db_path:
        entries = load_tuning_db(db_path)
        if platform is None:
            platform = current_platform()
        hit = lookup_tuning_db(
            entries, n, n_markers, spectral_dtype=spectral_dtype,
            platform=platform, chunk_length=chunk_length)
        if hit is not None:
            _HITS.inc()
            return _validate(str(hit.get("engine", "")).lower(),
                             f"tuning DB {db_path}")
        _FALLBACKS.inc()
    return default_rule(n, n_markers, support)
