"""2D periodic elastic-membrane model (the ex0-equivalent acceptance config).

Reference parity: ``examples/IB/explicit/ex0`` — a closed elastic fiber
loop (springs between adjacent markers, optional beams) immersed in a
periodic incompressible fluid on a single uniform level with the IB_4
delta (SURVEY.md §7.2 stage 5, BASELINE.json configs[0]).

The builder accepts either programmatic parameters or an ``InputDatabase``
with the reference-style sections (CartesianGeometry,
INSStaggeredHierarchyIntegrator, IBMethod/Membrane keys).
"""

from __future__ import annotations

import math
from typing import Optional, Tuple

import numpy as np

from ibamr_tpu.grid import StaggeredGrid
from ibamr_tpu.integrators.ib import IBExplicitIntegrator, IBMethod, IBState
from ibamr_tpu.integrators.ins import INSStaggeredIntegrator
from ibamr_tpu.io.structures import StructureData


def make_circle_membrane(num_markers: int, radius: float,
                         center: Tuple[float, float],
                         stiffness: float,
                         rest_length_factor: float = 1.0,
                         aspect: float = 1.0,
                         bend_rigidity: float = 0.0) -> StructureData:
    """Closed marker loop with nearest-neighbor springs (and optional
    beams). ``aspect`` != 1 makes an ellipse (the classic relaxation test:
    an ellipse with taut springs relaxes toward a circle while the
    enclosed area is conserved by incompressibility).
    ``rest_length_factor`` scales the natural rest length: < 1 makes the
    membrane everywhere-taut."""
    theta = 2.0 * math.pi * np.arange(num_markers) / num_markers
    verts = np.stack([center[0] + radius * aspect * np.cos(theta),
                      center[1] + (radius / aspect) * np.sin(theta)], axis=1)
    ds = 2.0 * math.pi * radius / num_markers
    idx0 = np.arange(num_markers)
    idx1 = (idx0 + 1) % num_markers
    springs = np.stack([
        idx0, idx1,
        np.full(num_markers, stiffness),
        np.full(num_markers, ds * rest_length_factor)], axis=1)
    data = StructureData(name="membrane2d", vertices=verts, springs=springs)
    if bend_rigidity > 0.0:
        prev = (idx0 - 1) % num_markers
        beams = np.stack([
            prev, idx0, idx1,
            np.full(num_markers, bend_rigidity)], axis=1)
        data.beams = beams
    return data


def build_membrane_example(
        n_cells: int = 64,
        num_markers: int = 128,
        radius: float = 0.25,
        aspect: float = 1.0,
        stiffness: float = 1.0,
        rest_length_factor: float = 0.5,
        rho: float = 1.0,
        mu: float = 0.05,
        kernel: str = "IB_4",
        convective_op_type: str = "centered",
        dtype=None,
        input_db=None) -> Tuple[IBExplicitIntegrator, IBState]:
    """Assemble the ex0-equivalent simulation. If ``input_db`` is given,
    reference-style sections override the keyword defaults."""
    import jax.numpy as jnp

    if dtype is None:
        dtype = jnp.float32

    n = (n_cells, n_cells)
    x_lo, x_up = (0.0, 0.0), (1.0, 1.0)
    if input_db is not None:
        geo = input_db.get_database_with_default("CartesianGeometry")
        n = tuple(int(v) for v in geo.get_int_array("n_cells", list(n)))
        x_lo = tuple(float(v) for v in geo.get_array("x_lo", list(x_lo)))
        x_up = tuple(float(v) for v in geo.get_array("x_up", list(x_up)))
        ins_db = input_db.get_database_with_default(
            "INSStaggeredHierarchyIntegrator")
        rho = ins_db.get_float("rho", rho)
        mu = ins_db.get_float("mu", mu)
        convective_op_type = ins_db.get_string("convective_op_type",
                                               convective_op_type)
        ib_db = input_db.get_database_with_default("IBMethod")
        kernel = ib_db.get_string("delta_fcn", kernel)
        mem = input_db.get_database_with_default("Membrane")
        num_markers = mem.get_int("num_markers", num_markers)
        radius = mem.get_float("radius", radius)
        aspect = mem.get_float("aspect", aspect)
        stiffness = mem.get_float("stiffness", stiffness)
        rest_length_factor = mem.get_float("rest_length_factor",
                                           rest_length_factor)

    grid = StaggeredGrid(n=n, x_lo=x_lo, x_up=x_up)
    ins = INSStaggeredIntegrator(grid, rho=rho, mu=mu,
                                 convective_op_type=convective_op_type,
                                 dtype=dtype)
    center = tuple(0.5 * (lo + hi) for lo, hi in zip(x_lo, x_up))
    structure = make_circle_membrane(
        num_markers, radius, center=center, stiffness=stiffness,
        rest_length_factor=rest_length_factor, aspect=aspect)
    ib = IBMethod(structure.force_specs(dtype=dtype), kernel=kernel)
    integ = IBExplicitIntegrator(ins, ib, scheme="midpoint")
    state = integ.initialize(structure.vertices)
    return integ, state
