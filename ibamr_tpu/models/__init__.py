from ibamr_tpu.models.fe_disc2d import build_fe_disc_example
from ibamr_tpu.models.membrane2d import (
    build_membrane_example, make_circle_membrane)

__all__ = ["build_fe_disc_example", "build_membrane_example",
           "make_circle_membrane"]
