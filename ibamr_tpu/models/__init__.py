from ibamr_tpu.models.membrane2d import (
    build_membrane_example, make_circle_membrane)

__all__ = ["build_membrane_example", "make_circle_membrane"]
