"""2D immersed elastic FE disc (the IBFE/explicit/ex0-equivalent config).

Reference parity: ``examples/IBFE/explicit/ex0`` — a soft hyperelastic
disc (TRI3 mesh, neo-Hookean-type material) immersed in a periodic
incompressible fluid, coupled with regularized deltas
(SURVEY.md §7.2 stage 10, BASELINE.json configs).

The classic validation: pre-stretch the disc with an affine area-
preserving map; released in quiescent viscous fluid it oscillates and
relaxes back toward the round reference shape while incompressibility
holds its area fixed.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from ibamr_tpu.fe import disc_mesh, neo_hookean
from ibamr_tpu.grid import StaggeredGrid
from ibamr_tpu.integrators.ib import IBExplicitIntegrator, IBState
from ibamr_tpu.integrators.ibfe import IBFEMethod
from ibamr_tpu.integrators.ins import INSStaggeredIntegrator


def build_fe_disc_example(
        n_cells: int = 64,
        n_rings: int = 6,
        radius: float = 0.2,
        stretch: float = 1.0,
        mu_s: float = 1.0,
        lam_s: float = 4.0,
        rho: float = 1.0,
        mu: float = 0.05,
        kernel: str = "IB_4",
        coupling: str = "unified",
        convective_op_type: str = "centered",
        dtype=None,
        input_db=None) -> Tuple[IBExplicitIntegrator, IBState]:
    """Assemble the IBFE-ex0-equivalent simulation.

    ``stretch`` != 1 applies the area-preserving pre-deformation
    diag(stretch, 1/stretch) about the disc center.
    """
    import jax.numpy as jnp

    if dtype is None:
        dtype = jnp.float32

    n = (n_cells, n_cells)
    x_lo, x_up = (0.0, 0.0), (1.0, 1.0)
    if input_db is not None:
        geo = input_db.get_database_with_default("CartesianGeometry")
        n = tuple(int(v) for v in geo.get_int_array("n_cells", list(n)))
        x_lo = tuple(float(v) for v in geo.get_array("x_lo", list(x_lo)))
        x_up = tuple(float(v) for v in geo.get_array("x_up", list(x_up)))
        ins_db = input_db.get_database_with_default(
            "INSStaggeredHierarchyIntegrator")
        rho = ins_db.get_float("rho", rho)
        mu = ins_db.get_float("mu", mu)
        convective_op_type = ins_db.get_string("convective_op_type",
                                               convective_op_type)
        fe_db = input_db.get_database_with_default("IBFEMethod")
        kernel = fe_db.get_string("delta_fcn", kernel)
        coupling = fe_db.get_string("coupling", coupling)
        disc = input_db.get_database_with_default("Disc")
        n_rings = disc.get_int("n_rings", n_rings)
        radius = disc.get_float("radius", radius)
        stretch = disc.get_float("stretch", stretch)
        mu_s = disc.get_float("shear_modulus", mu_s)
        lam_s = disc.get_float("bulk_modulus", lam_s)

    grid = StaggeredGrid(n=n, x_lo=x_lo, x_up=x_up)
    ins = INSStaggeredIntegrator(grid, rho=rho, mu=mu,
                                 convective_op_type=convective_op_type,
                                 dtype=dtype)
    center = tuple(0.5 * (lo + hi) for lo, hi in zip(x_lo, x_up))
    mesh = disc_mesh(radius=radius, center=center, n_rings=n_rings)
    fe = IBFEMethod(mesh, neo_hookean(mu_s, lam_s), kernel=kernel,
                    coupling=coupling, dtype=dtype)
    integ = IBExplicitIntegrator(ins, fe, scheme="midpoint")

    X0 = mesh.nodes.copy()
    if stretch != 1.0:
        c = np.asarray(center)
        A = np.diag([stretch, 1.0 / stretch])
        X0 = (X0 - c) @ A.T + c
    state = integ.initialize(X0)
    return integ, state
