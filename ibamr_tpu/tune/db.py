"""Tuning-DB schema v1: versioned, provenance-stamped measured winners.

One DB document::

    {"schema": 1,
     "entries": [
       {"engine": "packed_bf16",
        "n": [256, 256, 256],              # match fields (resolver
        "markers_min": 49928,              #  vocabulary — see
        "markers_max": 199712,             #  models/engine_resolver.py)
        "platform": "tpu",
        "spectral_dtype": "f32",
        "measured": {                      # the evidence
          "steps_per_s": 10.276,
          "runner_up": "pallas_packed",
          "runner_up_steps_per_s": 9.36,
          "margin": 1.098,                 # winner / runner-up
          "chunk_length": 4},
        "provenance": {                    # where the number came from
          "platform": "tpu",               # resolver SKIPS on mismatch
          "device_kind": "tpu v5 lite",
          "jax_version": "0.4.x",
          "git_rev": "96498b2",
          "fingerprint": {...},            # canonicalized subset
          "timestamp": "2026-08-06"}}]}

Validation (:func:`validate_db`) is the tier-1 gate's body: schema
version, engine vocabulary, match-field types, and the shadowed-entry
lint — an entry no query can ever reach (every query it matches is won
by a more-specific-or-earlier entry) is DEAD DATA and fails the gate
rather than silently rotting in the file. Writes are atomic
(tmp + ``os.replace``) like every other committed artifact.

The provenance ``timestamp`` is CALLER-SUPPLIED (ISO date string):
this module never reads the clock, so a publication is reproducible
from its inputs.
"""

from __future__ import annotations

import json
import os
from typing import Optional, Sequence

from ibamr_tpu.models.engine_resolver import (DB_SCHEMA, MATCH_FIELDS,
                                              RESOLVED_ENGINES,
                                              entry_specificity,
                                              normalize_spectral_dtype)

_DOC = ("Measured-search tuning DB (ibamr_tpu/tune/, docs/TUNING.md): "
        "per-configuration transfer-engine winners consulted by "
        "models/engine_resolver.py (most-specific match wins; entries "
        "whose provenance.platform differs from the running backend "
        "are skipped). Validated by tools/tune.py check and the tier-1 "
        "gate in tests/test_tune.py; re-measured/re-published by "
        "tools/relay_watch.py on every healthy TPU window.")


def new_db() -> dict:
    return {"schema": DB_SCHEMA, "_doc": _DOC, "entries": []}


def load_db(path: str) -> dict:
    """The full DB document (not just entries — the resolver's
    ``load_tuning_db`` reads those); raises on unreadable input.
    Legacy schema-less docs are upgraded in memory."""
    with open(path) as f:
        doc = json.load(f)
    if not isinstance(doc, dict):
        raise ValueError(f"tuning DB {path}: expected a JSON object")
    doc.setdefault("schema", DB_SCHEMA)
    doc.setdefault("entries", [])
    return doc


def save_db(doc: dict, path: str) -> None:
    """Atomic write (tmp + ``os.replace``) — a torn publish must never
    leave a half-written DB for the resolver to choke on."""
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)


def make_provenance(platform: str, timestamp: str, *,
                    device_kind: Optional[str] = None,
                    jax_version: Optional[str] = None,
                    git_rev: Optional[str] = None,
                    fingerprint: Optional[dict] = None,
                    source: Optional[str] = None) -> dict:
    """Provenance block. ``platform`` is mandatory — an entry with no
    platform provenance would steer every backend, which is exactly
    the cross-platform poisoning the schema exists to prevent.
    ``timestamp`` is caller-supplied (ISO date)."""
    if not platform:
        raise ValueError("provenance requires a platform")
    prov = {"platform": str(platform).lower(), "timestamp": timestamp}
    if device_kind:
        prov["device_kind"] = device_kind
    if jax_version:
        prov["jax_version"] = jax_version
    if git_rev:
        prov["git_rev"] = git_rev
    if fingerprint:
        from ibamr_tpu.utils.flight_recorder import canonicalize
        prov["fingerprint"] = canonicalize(fingerprint)
    if source:
        prov["source"] = source
    return prov


def make_entry(engine: str, *, n: Optional[Sequence[int]] = None,
               n_cells: Optional[int] = None,
               markers_min: Optional[int] = None,
               markers_max: Optional[int] = None,
               spectral_dtype: Optional[str] = None,
               platform: Optional[str] = None,
               chunk_length: Optional[int] = None,
               measured: Optional[dict] = None,
               provenance: Optional[dict] = None) -> dict:
    entry: dict = {"engine": engine}
    if n is not None:
        entry["n"] = [int(v) for v in n]
    if n_cells is not None:
        entry["n_cells"] = int(n_cells)
    if markers_min is not None:
        entry["markers_min"] = int(markers_min)
    if markers_max is not None:
        entry["markers_max"] = int(markers_max)
    if spectral_dtype is not None:
        entry["spectral_dtype"] = normalize_spectral_dtype(
            spectral_dtype)
    if platform is not None:
        entry["platform"] = str(platform).lower()
    if chunk_length is not None:
        entry["chunk_length"] = int(chunk_length)
    if measured is not None:
        entry["measured"] = dict(measured)
    if provenance is not None:
        entry["provenance"] = dict(provenance)
    return entry


def _match_key(entry: dict) -> tuple:
    """The identity a publication replaces on: the full match-field
    tuple plus the provenance platform (a TPU winner and a CPU winner
    for the same key coexist — the resolver's provenance skip keeps
    them apart at lookup time)."""
    prov = entry.get("provenance") or {}
    key = [(f, json.dumps(entry.get(f))) for f in MATCH_FIELDS]
    key.append(("provenance.platform", prov.get("platform")))
    return tuple(key)


def merge_entry(doc: dict, entry: dict) -> dict:
    """Insert ``entry``, replacing any existing entry with the same
    match identity (re-publication updates measurements in place
    instead of accreting shadowed duplicates)."""
    entries = doc.setdefault("entries", [])
    key = _match_key(entry)
    for i, old in enumerate(entries):
        if isinstance(old, dict) and _match_key(old) == key:
            entries[i] = entry
            return doc
    entries.append(entry)
    return doc


# ---------------------------------------------------------------------------
# validation + shadow lint
# ---------------------------------------------------------------------------

def _effective(entry: dict) -> dict:
    """Match constraints with the provenance platform folded in — for
    shadow analysis the provenance skip acts exactly like a platform
    pin (both restrict which queries an entry can serve)."""
    eff = {f: entry.get(f) for f in MATCH_FIELDS}
    prov_plat = (entry.get("provenance") or {}).get("platform")
    if eff["platform"] is None and prov_plat is not None:
        eff["platform"] = prov_plat
    return eff


def _implies(b: dict, a: dict) -> bool:
    """True when every query matching constraints ``b`` also matches
    ``a`` (a's constraints are implied by b's)."""
    for f in ("n", "spectral_dtype", "platform", "chunk_length"):
        if a[f] is not None and json.dumps(a[f]) != json.dumps(b[f]):
            return False
    if a["n_cells"] is not None:
        cubic = (b["n"] is not None
                 and all(int(v) == int(a["n_cells"]) for v in b["n"]))
        if b["n_cells"] != a["n_cells"] and not cubic:
            return False
    if a["markers_min"] is not None:
        if b["markers_min"] is None \
                or int(b["markers_min"]) < int(a["markers_min"]):
            return False
    if a["markers_max"] is not None:
        if b["markers_max"] is None \
                or int(b["markers_max"]) > int(a["markers_max"]):
            return False
    return True


def shadowed_entries(entries: list) -> list:
    """Indices of FULLY-shadowed entries: entry j is dead when some
    entry i matches every query j matches AND wins the
    most-specific/file-order tiebreak on all of them (strictly higher
    specificity, or equal specificity and earlier in the file). Dead
    entries are a lint ERROR — they read as configuration but change
    nothing. Returns ``[(j, i, reason), ...]``."""
    out = []
    effs = [_effective(e) if isinstance(e, dict) else None
            for e in entries]
    scores = [entry_specificity(e) if isinstance(e, dict) else -1
              for e in entries]
    for j, ej in enumerate(entries):
        if effs[j] is None:
            continue
        for i, ei in enumerate(entries):
            if i == j or effs[i] is None:
                continue
            if not _implies(effs[j], effs[i]):
                continue
            if scores[i] > scores[j] or (scores[i] == scores[j]
                                         and i < j):
                out.append((
                    j, i,
                    f"entry[{j}] ({ej.get('engine')}) is fully "
                    f"shadowed by entry[{i}] ({ei.get('engine')}): "
                    f"every query it matches is won by entry[{i}] "
                    f"(specificity {scores[i]} vs {scores[j]}"
                    + (", earlier in file" if scores[i] == scores[j]
                       else "") + ")"))
                break
    return out


def validate_db(doc: dict) -> list:
    """Problem strings (empty = valid): schema version, entry shape,
    engine vocabulary, match-field types, marker-band sanity, and the
    shadowed-entry lint. The tier-1 gate and ``tools/tune.py check``
    both run exactly this."""
    problems = []
    if doc.get("schema") != DB_SCHEMA:
        problems.append(f"schema: expected {DB_SCHEMA}, "
                        f"got {doc.get('schema')!r}")
    entries = doc.get("entries")
    if not isinstance(entries, list):
        problems.append("entries: expected a list")
        return problems
    for i, e in enumerate(entries):
        where = f"entries[{i}]"
        if not isinstance(e, dict):
            problems.append(f"{where}: expected an object")
            continue
        eng = e.get("engine")
        if eng not in RESOLVED_ENGINES:
            problems.append(
                f"{where}.engine: {eng!r} not in RESOLVED_ENGINES")
        for f in ("n_cells", "markers_min", "markers_max",
                  "chunk_length"):
            if e.get(f) is not None and not isinstance(e[f], int):
                problems.append(f"{where}.{f}: expected an integer, "
                                f"got {e[f]!r}")
        if e.get("n") is not None and (
                not isinstance(e["n"], list)
                or not all(isinstance(v, int) for v in e["n"])):
            problems.append(f"{where}.n: expected a list of integers")
        if (isinstance(e.get("markers_min"), int)
                and isinstance(e.get("markers_max"), int)
                and e["markers_min"] > e["markers_max"]):
            problems.append(f"{where}: empty marker band "
                            f"[{e['markers_min']}, {e['markers_max']}]")
        m = e.get("measured")
        if m is not None:
            if not isinstance(m, dict):
                problems.append(f"{where}.measured: expected an object")
            elif not isinstance(m.get("steps_per_s"), (int, float)):
                problems.append(
                    f"{where}.measured.steps_per_s: expected a number")
        prov = e.get("provenance")
        if prov is not None and (not isinstance(prov, dict)
                                 or not prov.get("platform")):
            problems.append(
                f"{where}.provenance: expected an object with a "
                f"'platform' field")
    for _, _, reason in shadowed_entries(entries):
        problems.append(f"shadow lint: {reason}")
    return problems
