"""Measured trials: compile once through the AOT cache, time warm steps.

A trial is one :class:`~ibamr_tpu.tune.space.Candidate` built into a
real integrator (``engine_fallback=False`` — a degraded build would
time the WRONG engine and poison the DB) whose L-step scan chunk is
AOT-compiled through the PR-11 :class:`ExecutableCache`. The compile
is paid once per candidate family ever (the second trial of a
candidate is a cache HIT — pinned by tests/test_tune.py); the timed
leg runs only warm executions under an ``obs.span`` with the
async-dispatch block-on discipline (drain before start, block before
stop — the ``tools/microbench_*`` idiom), so a trial measures steady
steps/s, not dispatch or compile.

Chunk length is a REAL graph knob, not a timing detail: the scan of
length L is its own executable (cache-key material: ``kind:
tune_chunk, length: L``), and longer chunks amortize per-dispatch
host cost — which is why the search grid includes it and the DB can
pin it.

Every trial lands on the telemetry bus as a ``tune_trial`` ledger
record plus ``tune_{trials,errors}_total`` counters, so
``tools/obs.py summary`` renders the measured ranking next to the
serving block.
"""

from __future__ import annotations

import time
from dataclasses import asdict, dataclass, field
from typing import Optional, Sequence

from ibamr_tpu import obs as _obs
from ibamr_tpu.tune.space import (Candidate, DEFAULT_ENGINES,
                                  enumerate_space, make_probe_fn)

_TRIALS = _obs.counter("tune_trials_total")
_PRUNED = _obs.counter("tune_pruned_total")
_ERRORS = _obs.counter("tune_errors_total")


@dataclass
class TrialResult:
    candidate: Candidate
    steps_per_s: float = 0.0
    ms_per_step: float = 0.0
    compile_s: float = 0.0
    cache_hit: bool = False
    recompiles: int = 0
    error: Optional[str] = None

    def row(self) -> dict:
        out = asdict(self.candidate)
        out.update(steps_per_s=round(self.steps_per_s, 4),
                   ms_per_step=round(self.ms_per_step, 4),
                   compile_s=round(self.compile_s, 3),
                   cache_hit=self.cache_hit, error=self.error)
        return out


def _engine_arg(engine: str):
    # the build_shell_example use_fast_interaction vocabulary
    return {"scatter": False, "mxu": True}.get(engine, engine)


def chunk_callable(integ, length: int):
    """The L-step scan chunk the trial times — one executable per
    (family, length), exactly the dispatch-amortization graph a
    production driver runs."""
    import jax

    def chunk(state, dt):
        def body(s, _):
            return integ.step(s, dt), None
        s, _ = jax.lax.scan(body, state, None, length=int(length))
        return s
    return chunk


def run_trial(candidate: Candidate, *, n_cells: int = 16,
              n_lat: int = 8, n_lon: int = 16, dt: float = 5e-5,
              reps: int = 3, mu: float = 0.05, cache=None,
              label: str = "") -> TrialResult:
    """One measured trial. Build failures are reported in
    ``TrialResult.error`` (counted), never raised — the search must
    finish its grid even when one candidate dies on this backend."""
    import jax

    from ibamr_tpu.models.shell3d import build_shell_example
    from ibamr_tpu.serve import aot_cache

    cache = cache if cache is not None else aot_cache.get_cache()
    L = int(candidate.chunk_length)
    res = TrialResult(candidate=candidate)
    try:
        integ, state = build_shell_example(
            n_cells=n_cells, n_lat=n_lat, n_lon=n_lon, radius=0.25,
            aspect=1.2, stiffness=1.0, rest_length_factor=0.75,
            mu=mu, use_fast_interaction=_engine_arg(candidate.engine),
            spectral_dtype=candidate.spectral_dtype,
            engine_fallback=False)
        fp = aot_cache.step_fingerprint(integ)
        before = cache.stats()
        chunk = chunk_callable(integ, L)
        entry = cache.get_or_compile(
            fp,
            lambda: aot_cache.aot_compile(chunk, (state, dt)),
            extra={"kind": "tune_chunk", "length": L,
                   "args": aot_cache.arg_signature((state, dt))},
            label=label or f"tune:{candidate.label()}")
        after = cache.stats()
        res.compile_s = entry.compile_s
        res.cache_hit = after["hits"] > before["hits"]
        res.recompiles = after["misses"] - before["misses"]
        exe = entry.executable
        with _obs.span("tune/trial", engine=candidate.engine,
                       spectral_dtype=candidate.spectral_dtype,
                       chunk_length=L, n=n_cells):
            jax.block_until_ready(exe(state, dt))   # drain warm-up
            t0 = time.perf_counter()
            out = state
            for _ in range(int(reps)):
                out = exe(out, dt)
            jax.block_until_ready(out)
            elapsed = time.perf_counter() - t0
        per_step = elapsed / max(int(reps) * L, 1)
        res.steps_per_s = 1.0 / max(per_step, 1e-12)
        res.ms_per_step = per_step * 1e3
        _TRIALS.inc()
    except Exception as e:  # noqa: BLE001 - the grid must finish
        res.error = f"{type(e).__name__}: {e}"
        _ERRORS.inc()
    _obs.emit("tune_trial", n=n_cells, markers=n_lat * n_lon,
              engine=candidate.engine,
              spectral_dtype=candidate.spectral_dtype, chunk_length=L,
              steps_per_s=round(res.steps_per_s, 4),
              compile_s=round(res.compile_s, 3),
              cache_hit=res.cache_hit, error=res.error)
    return res


@dataclass
class SearchResult:
    config: dict
    trials: list = field(default_factory=list)
    pruned: list = field(default_factory=list)

    def ranking(self) -> list:
        ok = [t for t in self.trials if t.error is None]
        return sorted(ok, key=lambda t: t.steps_per_s, reverse=True)

    def winner(self) -> Optional[TrialResult]:
        r = self.ranking()
        return r[0] if r else None

    def runner_up(self) -> Optional[TrialResult]:
        """Best trial of a DIFFERENT engine than the winner — the
        margin the check gate re-validates is engine-vs-engine, not
        chunk-length-vs-chunk-length of the same engine."""
        r = self.ranking()
        if not r:
            return None
        return next((t for t in r[1:]
                     if t.candidate.engine != r[0].candidate.engine),
                    None)

    def to_dict(self) -> dict:
        w, ru = self.winner(), self.runner_up()
        margin = (round(w.steps_per_s / max(ru.steps_per_s, 1e-12), 4)
                  if w and ru else None)
        return {
            "config": self.config,
            "trials": [t.row() for t in self.trials],
            "pruned": [{**asdict(c), "reason": r}
                       for c, r in self.pruned],
            "winner": w.row() if w else None,
            "runner_up": ru.row() if ru else None,
            "margin": margin,
        }


def search(*, n_cells: int = 16, n_lat: int = 8, n_lon: int = 16,
           engines: Sequence[str] = DEFAULT_ENGINES,
           spectral_dtypes: Sequence[str] = ("f32", "bf16"),
           chunk_lengths: Sequence[int] = (1, 4), reps: int = 3,
           dt: float = 5e-5, probe: bool = True, cache=None,
           kernel: str = "IB_4") -> SearchResult:
    """Walk the engine x spectral_dtype x chunk-length grid for ONE
    configuration key, measured. Ineligible candidates are pruned
    statically (never timed); Pallas candidates are compile-probe
    gated when ``probe``."""
    from ibamr_tpu.ops.delta import get_kernel

    support, _ = get_kernel(kernel)
    n = (int(n_cells),) * 3
    n_markers = int(n_lat) * int(n_lon)
    probe_fn = (make_probe_fn(n, n_lat, n_lon, kernel=kernel)
                if probe else None)
    with _obs.span("tune/search", n=n_cells, markers=n_markers):
        candidates, pruned = enumerate_space(
            n, n_markers, support, engines=tuple(engines),
            spectral_dtypes=tuple(spectral_dtypes),
            chunk_lengths=tuple(chunk_lengths), probe_fn=probe_fn)
        for _ in pruned:
            _PRUNED.inc()
        result = SearchResult(
            config={"n": list(n), "n_cells": int(n_cells),
                    "n_lat": int(n_lat), "n_lon": int(n_lon),
                    "markers": n_markers, "dt": dt, "reps": int(reps),
                    "engines": list(engines),
                    "spectral_dtypes": [str(s) for s in spectral_dtypes],
                    "chunk_lengths": [int(L) for L in chunk_lengths]},
            pruned=pruned)
        for cand in candidates:
            result.trials.append(run_trial(
                cand, n_cells=n_cells, n_lat=n_lat, n_lon=n_lon,
                dt=dt, reps=reps, cache=cache))
    return result


def db_entry_from_search(result: SearchResult, *, platform: str,
                         timestamp: str, device_kind=None,
                         jax_version=None, git_rev=None,
                         source=None) -> Optional[dict]:
    """The publication: winner -> one schema-v1 DB entry whose match
    fields pin the measured configuration (exact grid, factor-2 marker
    band, spectral dtype, platform) and whose provenance pins the
    backend it was measured on. Returns None when nothing ran."""
    from ibamr_tpu.tune import db as _db

    w, ru = result.winner(), result.runner_up()
    if w is None:
        return None
    n_markers = result.config["markers"]
    measured = {"steps_per_s": round(w.steps_per_s, 4),
                "chunk_length": w.candidate.chunk_length,
                "reps": result.config["reps"],
                "n_lat": result.config["n_lat"],
                "n_lon": result.config["n_lon"]}
    if ru is not None:
        measured.update(
            runner_up=ru.candidate.engine,
            runner_up_steps_per_s=round(ru.steps_per_s, 4),
            runner_up_chunk_length=ru.candidate.chunk_length,
            margin=round(w.steps_per_s / max(ru.steps_per_s, 1e-12),
                         4))
    prov = _db.make_provenance(
        platform, timestamp, device_kind=device_kind,
        jax_version=jax_version, git_rev=git_rev, source=source)
    return _db.make_entry(
        w.candidate.engine, n=result.config["n"],
        markers_min=max(1, n_markers // 2), markers_max=n_markers * 2,
        spectral_dtype=w.candidate.spectral_dtype, platform=platform,
        measured=measured, provenance=prov)
