"""Search-space enumeration with static pruning.

A trial costs an AOT compile plus timed warm steps; a candidate that
cannot ship must never reach the runner. Pruning is STATIC (geometry
and BC facts the engine constructors themselves enforce) plus an
optional compile probe for the Pallas family:

- **tile divisibility + minimum extent** — every non-scatter engine
  blocks the xy plane in 8-tiles and needs the ``make_geometry``
  minimum extent (``tile + support + 1``), the same facts
  ``default_rule`` promotes on;
- **packed3 z tile** — the z-blocked layout additionally needs a
  valid z tile (16 or 8 dividing the z extent with footprint room) —
  ``shell3d.construct_transfer_engine`` raises on exactly this;
- **wall-BC bf16 refusal** — the bf16/split-real spectral transform
  path is periodic-only; a non-periodic config prunes every
  ``spectral_dtype="bf16"`` candidate instead of timing a
  configuration the solver would refuse;
- **Pallas compile probe** — the Pallas-backed engines have failed to
  compile in the field (the round-2 remote-compile stall); with a
  ``probe_fn`` the enumeration trace+compiles each Pallas candidate
  through the PR-2 probe machinery
  (``shell3d.probe_transfer_engine``) and prunes the ones that die.

The marker-count heuristic (``n_markers >= 4096``) is deliberately
NOT a pruning rule: it is exactly the hand-tuned promotion threshold
this subsystem replaces with measurement — small-marker configs keep
their packed candidates and the measurement decides.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Optional, Sequence, Tuple

from ibamr_tpu.models.engine_resolver import RESOLVED_ENGINES

# the default searched engine menu: the r5 shootout set. hybrid
# aliases and "pallas" (superseded by pallas_packed at every measured
# size) stay out of the default menu but remain valid --engines args.
DEFAULT_ENGINES = ("scatter", "packed", "packed_bf16", "pallas_packed",
                   "packed3", "packed3_bf16", "mxu", "mxu_bf16")

# engines whose compile path has actually failed in the field — gated
# by a compile probe when one is supplied (shell3d._PROBED_ENGINES
# plus plain "pallas")
PROBED_ENGINES = frozenset(
    {"pallas", "pallas_packed", "hybrid_packed", "hybrid_packed_bf16",
     "hybrid_bf16"})

_PACKED3 = ("packed3", "packed3_bf16")


@dataclass(frozen=True)
class Candidate:
    """One point of the engine x spectral_dtype x chunk-length grid."""
    engine: str
    spectral_dtype: str = "f32"
    chunk_length: int = 1

    def label(self) -> str:
        return (f"{self.engine}/{self.spectral_dtype}"
                f"/L{self.chunk_length}")


def _engine_eligible(engine: str, n: Sequence[int],
                     support: int) -> Optional[str]:
    """Static geometry eligibility; returns a prune reason or None."""
    if engine == "scatter":
        return None                      # the unconditional baseline
    if not all(v % 8 == 0 for v in n[:-1]):
        return (f"xy extents {tuple(n[:-1])} not divisible by the "
                f"8-tile")
    if not all(v >= 8 + support + 1 for v in n[:-1]):
        return (f"xy extents {tuple(n[:-1])} below the make_geometry "
                f"minimum (tile + support + 1 = {8 + support + 1})")
    if engine in _PACKED3:
        tz = next((t for t in (16, 8)
                   if n[-1] % t == 0 and n[-1] >= t + support + 1
                   and t >= support + 1), None)
        if tz is None:
            return (f"no valid z tile for n_z = {n[-1]} "
                    f"(need 8 or 16 dividing it with footprint room)")
    return None


def enumerate_space(
        n: Sequence[int], n_markers: int, support: int, *,
        engines: Sequence[str] = DEFAULT_ENGINES,
        spectral_dtypes: Sequence[str] = ("f32", "bf16"),
        chunk_lengths: Sequence[int] = (1, 4),
        bc: str = "periodic",
        probe_fn: Optional[Callable[[str], None]] = None,
) -> Tuple[list, list]:
    """``(candidates, pruned)`` for one configuration key. ``pruned``
    is ``[(Candidate, reason), ...]`` — every grid point is accounted
    for, nothing is silently dropped. ``probe_fn(engine)`` raises (or
    returns) per Pallas-family engine; when omitted, probing is skipped
    (pure-static mode — the runner's own build still degrades safely).
    A probe failure prunes EVERY candidate of that engine."""
    for e in engines:
        if e not in RESOLVED_ENGINES:
            raise ValueError(
                f"unknown engine {e!r} in the search menu; expected "
                f"names from {RESOLVED_ENGINES}")
    candidates, pruned = [], []
    probe_verdict: dict = {}
    for engine in engines:
        geo_reason = _engine_eligible(engine, n, support)
        if geo_reason is None and probe_fn is not None \
                and engine in PROBED_ENGINES:
            if engine not in probe_verdict:
                try:
                    probe_fn(engine)
                    probe_verdict[engine] = None
                except Exception as e:  # noqa: BLE001 - prune, not die
                    probe_verdict[engine] = (
                        f"compile probe failed "
                        f"({type(e).__name__}: {e})")
            geo_reason = probe_verdict[engine]
        for sd in spectral_dtypes:
            sd = str(sd).lower()
            for L in chunk_lengths:
                cand = Candidate(engine=engine, spectral_dtype=sd,
                                 chunk_length=int(L))
                if geo_reason is not None:
                    pruned.append((cand, geo_reason))
                elif sd == "bf16" and bc != "periodic":
                    pruned.append((
                        cand,
                        f"bf16 spectral transforms are periodic-only "
                        f"(bc={bc!r})"))
                else:
                    candidates.append(cand)
    return candidates, pruned


def make_probe_fn(n: Sequence[int], n_lat: int, n_lon: int,
                  kernel: str = "IB_4") -> Callable[[str], None]:
    """The real compile probe: construct the engine against the actual
    grid + a representative shell lattice and trace+compile a
    bucket/spread/interp composition (the PR-2 fallback machinery's
    build-time check). Raises on construction or compile failure."""
    def probe(engine: str) -> None:
        from ibamr_tpu.grid import StaggeredGrid
        from ibamr_tpu.models.shell3d import (construct_transfer_engine,
                                              make_spherical_shell,
                                              probe_transfer_engine)

        grid = StaggeredGrid(n=tuple(int(v) for v in n),
                             x_lo=(0.0,) * len(n), x_up=(1.0,) * len(n))
        s = make_spherical_shell(n_lat, n_lon, 0.25,
                                 tuple(0.5 for _ in n)[:3], 1.0,
                                 aspect=1.2)
        fast = construct_transfer_engine(engine, grid, s.vertices,
                                         kernel)
        probe_transfer_engine(fast, s.vertices)
    return probe
