"""Measured-search autotuner (docs/TUNING.md).

No static engine choice is right across the size range — the r5
on-chip shootout ranking *inverts* between 128^3 and 256^3 (PERF.md).
This package replaces hand-picked promotions with measurement in the
FFTW/ATLAS tradition:

- :mod:`ibamr_tpu.tune.space` — candidate enumeration with static
  pruning (tile divisibility, minimum extents, wall-BC bf16 refusal,
  Pallas compile-probe gating), so the search never times a candidate
  that can't ship;
- :mod:`ibamr_tpu.tune.runner` — measured trials compiled through the
  AOT executable cache (compile paid once per candidate family), warm
  steps timed under ``obs.span`` with the async-dispatch block-on
  discipline, per-trial ``tune_trial`` ledger records;
- :mod:`ibamr_tpu.tune.db` — the versioned, provenance-stamped
  ``TUNING_DB.json`` the resolver
  (:mod:`ibamr_tpu.models.engine_resolver`) consults: schema v1
  validation, shadowed-entry lint, atomic publication.

``tools/tune.py`` is the CLI (search/show/publish/check);
``tools/relay_watch.py`` runs ``search --publish`` on every healthy
TPU window so the committed defaults stay device-measured.
"""

from ibamr_tpu.tune.db import (load_db, make_entry, make_provenance,
                               merge_entry, save_db, shadowed_entries,
                               validate_db)
from ibamr_tpu.tune.space import Candidate, enumerate_space
from ibamr_tpu.tune.runner import TrialResult, run_trial, search

__all__ = [
    "Candidate", "TrialResult", "enumerate_space", "load_db",
    "make_entry", "make_provenance", "merge_entry", "run_trial",
    "save_db", "search", "shadowed_entries", "validate_db",
]
