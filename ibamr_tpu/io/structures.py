"""Readers/writers for the reference's structure input file formats.

Reference parity: ``IBStandardInitializer`` (P10) parsing of
``<name>.vertex/.spring/.beam/.target`` files (formats per SURVEY.md
Appendix B):

  name.vertex: line 1 = N;  then N lines  "x y [z]"
  name.spring: line 1 = M;  then M lines  "idx0 idx1 stiffness rest_length
                                           [force_fcn_idx]"
  name.beam:   line 1 = M;  then M lines  "prev mid next bend_rigidity
                                           [curvature components]"
  name.target: line 1 = M;  then M lines  "idx stiffness [damping]"

Indices are 0-based within the structure, as in the reference. Parsing is
host-side (NumPy); the result converts to device SoA specs via
``StructureData.force_specs()``. A writer is provided for tests and
example generation (the reference ships pre-generated files instead).
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from ibamr_tpu.ops import forces


def _read_table(path: str, min_cols: int, max_cols: int,
                what: str) -> np.ndarray:
    native = _read_table_native(path, min_cols, max_cols, what)
    if native is not None:
        return native
    with open(path) as f:
        tokens = f.read().split("\n")
    lines = [ln.split("#")[0].strip() for ln in tokens]
    lines = [ln for ln in lines if ln]
    if not lines:
        raise ValueError(f"{path}: empty {what} file")
    try:
        count = int(lines[0].split()[0])
    except ValueError:
        raise ValueError(f"{path}: first line must be the {what} count")
    rows = []
    for ln in lines[1:count + 1]:
        cols = ln.split()
        if not (min_cols <= len(cols) <= max_cols):
            raise ValueError(
                f"{path}: expected {min_cols}..{max_cols} columns, got "
                f"{len(cols)}: {ln!r}")
        rows.append([float(c) for c in cols])
    if len(rows) != count:
        raise ValueError(
            f"{path}: declared {count} {what} entries, found {len(rows)}")
    width = max(len(r) for r in rows)
    out = np.zeros((count, width))
    for i, r in enumerate(rows):
        out[i, :len(r)] = r
    return out


def _read_table_native(path: str, min_cols: int, max_cols: int,
                       what: str) -> Optional[np.ndarray]:
    """C++ fast path (io.native): same contract as the Python parser;
    None when the native library is unavailable."""
    from ibamr_tpu.io.native import parse_table_native

    with open(path, "rb") as f:
        text = f.read()
    try:
        parsed = parse_table_native(text, max_cols)
    except ValueError as e:
        raise ValueError(f"{path}: {e}")
    if parsed is None:
        return None
    rows, ncols = parsed
    if rows.shape[0] == 0:
        raise ValueError(f"{path}: empty {what} file")
    count_f = rows[0, 0]
    if not np.isfinite(count_f) or count_f != int(count_f) \
            or count_f < 0:
        raise ValueError(f"{path}: first line must be the {what} count")
    count = int(count_f)
    if rows.shape[0] - 1 < count:
        raise ValueError(
            f"{path}: declared {count} {what} entries, found "
            f"{rows.shape[0] - 1}")
    body = rows[1:count + 1]
    nc = ncols[1:count + 1]
    if count and not ((nc >= min_cols) & (nc <= max_cols)).all():
        bad = int(np.argmax((nc < min_cols) | (nc > max_cols)))
        raise ValueError(
            f"{path}: expected {min_cols}..{max_cols} columns, got "
            f"{int(nc[bad])} on entry {bad}")
    width = int(nc.max()) if count else min_cols
    out = body[:, :width].copy()
    # zero ONLY the pad slots (columns beyond each row's true count) —
    # a genuine 'nan' data value must survive, as in the Python parser
    if count:
        pad = np.arange(width)[None, :] >= nc[:, None]
        out[pad] = 0.0
    return out


@dataclass
class StructureData:
    """One structure's host-side data (the P10 'initializer' product)."""
    name: str
    vertices: np.ndarray                 # (N, dim)
    springs: Optional[np.ndarray] = None   # (M, >=4): idx0 idx1 k L0 [fcn]
    beams: Optional[np.ndarray] = None     # (M, >=4): prev mid next c [C0...]
    targets: Optional[np.ndarray] = None   # (M, >=2): idx kappa [damping]
    index_offset: int = 0                # global offset when concatenating
    extra: dict = field(default_factory=dict)

    @property
    def num_markers(self) -> int:
        return self.vertices.shape[0]

    @property
    def dim(self) -> int:
        return self.vertices.shape[1]

    def force_specs(self, dtype=None) -> forces.ForceSpecs:
        """Device SoA force specs with indices shifted by index_offset.
        ``dtype`` matches the simulation's state dtype (default f32)."""
        import jax.numpy as jnp

        if dtype is None:
            dtype = jnp.float32
        off = self.index_offset
        springs = beams = targets = None
        if self.springs is not None and len(self.springs):
            s = self.springs
            springs = forces.make_springs(
                s[:, 0].astype(np.int32) + off,
                s[:, 1].astype(np.int32) + off,
                s[:, 2], s[:, 3], dtype=dtype)
        if self.beams is not None and len(self.beams):
            b = self.beams
            curv = b[:, 4:4 + self.dim] if b.shape[1] >= 4 + self.dim else None
            beams = forces.make_beams(
                b[:, 0].astype(np.int32) + off,
                b[:, 1].astype(np.int32) + off,
                b[:, 2].astype(np.int32) + off,
                b[:, 3], curv, dim=self.dim, dtype=dtype)
        if self.targets is not None and len(self.targets):
            t = self.targets
            idx = t[:, 0].astype(np.int32)
            damping = t[:, 2] if t.shape[1] > 2 else None
            targets = forces.make_targets(
                idx + off, t[:, 1], self.vertices[idx], damping,
                dtype=dtype)
        return forces.ForceSpecs(springs=springs, beams=beams,
                                 targets=targets)


def read_structure(basename: str, dim: Optional[int] = None) -> StructureData:
    """Read ``basename.vertex`` (+ optional .spring/.beam/.target)."""
    vpath = basename + ".vertex"
    if not os.path.exists(vpath):
        raise FileNotFoundError(vpath)
    verts = _read_table(vpath, 2, 3, "vertex")
    if dim is not None:
        verts = verts[:, :dim]
    data = StructureData(name=os.path.basename(basename), vertices=verts)
    d = verts.shape[1]
    if os.path.exists(basename + ".spring"):
        data.springs = _read_table(basename + ".spring", 4, 5, "spring")
    if os.path.exists(basename + ".beam"):
        data.beams = _read_table(basename + ".beam", 4, 4 + d, "beam")
    if os.path.exists(basename + ".target"):
        data.targets = _read_table(basename + ".target", 2, 3, "target")
    return data


def write_structure(basename: str, data: StructureData) -> None:
    """Write the structure back out in the reference formats."""
    def _dump(path, arr, fmt):
        with open(path, "w") as f:
            f.write(f"{arr.shape[0]}\n")
            for row in arr:
                f.write(fmt(row) + "\n")

    _dump(basename + ".vertex", data.vertices,
          lambda r: " ".join(f"{v:.17g}" for v in r))
    if data.springs is not None:
        _dump(basename + ".spring", data.springs,
              lambda r: f"{int(r[0])} {int(r[1])} " +
              " ".join(f"{v:.17g}" for v in r[2:]))
    if data.beams is not None:
        _dump(basename + ".beam", data.beams,
              lambda r: f"{int(r[0])} {int(r[1])} {int(r[2])} " +
              " ".join(f"{v:.17g}" for v in r[3:]))
    if data.targets is not None:
        _dump(basename + ".target", data.targets,
              lambda r: f"{int(r[0])} " +
              " ".join(f"{v:.17g}" for v in r[1:]))
