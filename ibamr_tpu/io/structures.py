"""Readers/writers for the reference's structure input file formats.

Reference parity: ``IBStandardInitializer`` (P10) parsing of the full
``<name>.*`` menu (formats per SURVEY.md Appendix B; the .rod/.anchor/
.mass/.source/.inst column layouts are the canonical-IBAMR convention,
tagged [U] because the reference mount was empty at survey time):

  name.vertex: line 1 = N;  then N lines  "x y [z]"
  name.spring: line 1 = M;  then M lines  "idx0 idx1 stiffness rest_length
                                           [force_fcn_idx]"
  name.beam:   line 1 = M;  then M lines  "prev mid next bend_rigidity
                                           [curvature components]"
  name.target: line 1 = M;  then M lines  "idx stiffness [damping]"
  name.rod:    line 1 = M;  then M lines  "curr next ds a1 a2 a3 b1 b2 b3
                                           kappa1 kappa2 tau"
               (a* = bending/twist moduli, b* = shear/stretch moduli,
                kappa1/kappa2/tau = intrinsic curvature + twist —
                IBRodForceSpec's 10 material parameters, P12)
  name.anchor: line 1 = M;  then M lines  "idx"            (pinned nodes)
  name.mass:   line 1 = M;  then M lines  "idx mass [stiffness]"
               (massive nodes + penalty spring constant, P14)
  name.source: line 1 = M;  then M lines  "idx strength"   (P14 sources)
  name.inst:   line 1 = M;  then M lines  "idx meter_idx node_idx"
               (flow-meter membership, P13)

Indices are 0-based within the structure, as in the reference. Parsing is
host-side (NumPy); the result converts to device SoA specs via
``StructureData.force_specs()`` and the ``rod_specs / source_specs /
meter_specs / mass_arrays / anchors_to_targets`` helpers. A writer is
provided for tests and example generation (the reference ships
pre-generated files instead).
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from ibamr_tpu.ops import forces


def _read_table(path: str, min_cols: int, max_cols: int,
                what: str) -> np.ndarray:
    native = _read_table_native(path, min_cols, max_cols, what)
    if native is not None:
        return native
    with open(path) as f:
        tokens = f.read().split("\n")
    lines = [ln.split("#")[0].strip() for ln in tokens]
    lines = [ln for ln in lines if ln]
    if not lines:
        raise ValueError(f"{path}: empty {what} file")
    try:
        count = int(lines[0].split()[0])
    except ValueError:
        raise ValueError(f"{path}: first line must be the {what} count")
    rows = []
    for ln in lines[1:count + 1]:
        cols = ln.split()
        if not (min_cols <= len(cols) <= max_cols):
            raise ValueError(
                f"{path}: expected {min_cols}..{max_cols} columns, got "
                f"{len(cols)}: {ln!r}")
        rows.append([float(c) for c in cols])
    if len(rows) != count:
        raise ValueError(
            f"{path}: declared {count} {what} entries, found {len(rows)}")
    width = max(len(r) for r in rows)
    out = np.zeros((count, width))
    for i, r in enumerate(rows):
        out[i, :len(r)] = r
    return out


def _read_table_native(path: str, min_cols: int, max_cols: int,
                       what: str) -> Optional[np.ndarray]:
    """C++ fast path (io.native): same contract as the Python parser;
    None when the native library is unavailable."""
    from ibamr_tpu.io.native import parse_table_native

    with open(path, "rb") as f:
        text = f.read()
    try:
        parsed = parse_table_native(text, max_cols)
    except ValueError as e:
        raise ValueError(f"{path}: {e}")
    if parsed is None:
        return None
    rows, ncols = parsed
    if rows.shape[0] == 0:
        raise ValueError(f"{path}: empty {what} file")
    count_f = rows[0, 0]
    if not np.isfinite(count_f) or count_f != int(count_f) \
            or count_f < 0:
        raise ValueError(f"{path}: first line must be the {what} count")
    count = int(count_f)
    if rows.shape[0] - 1 < count:
        raise ValueError(
            f"{path}: declared {count} {what} entries, found "
            f"{rows.shape[0] - 1}")
    body = rows[1:count + 1]
    nc = ncols[1:count + 1]
    if count and not ((nc >= min_cols) & (nc <= max_cols)).all():
        bad = int(np.argmax((nc < min_cols) | (nc > max_cols)))
        raise ValueError(
            f"{path}: expected {min_cols}..{max_cols} columns, got "
            f"{int(nc[bad])} on entry {bad}")
    width = int(nc.max()) if count else min_cols
    out = body[:, :width].copy()
    # zero ONLY the pad slots (columns beyond each row's true count) —
    # a genuine 'nan' data value must survive, as in the Python parser
    if count:
        pad = np.arange(width)[None, :] >= nc[:, None]
        out[pad] = 0.0
    return out


@dataclass
class StructureData:
    """One structure's host-side data (the P10 'initializer' product)."""
    name: str
    vertices: np.ndarray                 # (N, dim)
    springs: Optional[np.ndarray] = None   # (M, >=4): idx0 idx1 k L0 [fcn]
    beams: Optional[np.ndarray] = None     # (M, >=4): prev mid next c [C0...]
    targets: Optional[np.ndarray] = None   # (M, >=2): idx kappa [damping]
    rods: Optional[np.ndarray] = None      # (M, 12): curr next + 10 params
    anchors: Optional[np.ndarray] = None   # (M, 1): idx
    masses: Optional[np.ndarray] = None    # (M, >=2): idx mass [stiffness]
    sources: Optional[np.ndarray] = None   # (M, 2): idx strength
    inst: Optional[np.ndarray] = None      # (M, 3): idx meter node
    index_offset: int = 0                # global offset when concatenating
    extra: dict = field(default_factory=dict)

    @property
    def num_markers(self) -> int:
        return self.vertices.shape[0]

    @property
    def dim(self) -> int:
        return self.vertices.shape[1]

    def force_specs(self, dtype=None) -> forces.ForceSpecs:
        """Device SoA force specs with indices shifted by index_offset.
        ``dtype`` matches the simulation's state dtype (default f32)."""
        import jax.numpy as jnp

        if dtype is None:
            dtype = jnp.float32
        off = self.index_offset
        springs = beams = targets = None
        if self.springs is not None and len(self.springs):
            s = self.springs
            springs = forces.make_springs(
                s[:, 0].astype(np.int32) + off,
                s[:, 1].astype(np.int32) + off,
                s[:, 2], s[:, 3], dtype=dtype)
        if self.beams is not None and len(self.beams):
            b = self.beams
            curv = b[:, 4:4 + self.dim] if b.shape[1] >= 4 + self.dim else None
            beams = forces.make_beams(
                b[:, 0].astype(np.int32) + off,
                b[:, 1].astype(np.int32) + off,
                b[:, 2].astype(np.int32) + off,
                b[:, 3], curv, dim=self.dim, dtype=dtype)
        if self.targets is not None and len(self.targets):
            t = self.targets
            idx = t[:, 0].astype(np.int32)
            damping = t[:, 2] if t.shape[1] > 2 else None
            targets = forces.make_targets(
                idx + off, t[:, 1], self.vertices[idx], damping,
                dtype=dtype)
        return forces.ForceSpecs(springs=springs, beams=beams,
                                 targets=targets)

    # -- converters for the extended-file menu -------------------------------
    def rod_specs(self, dtype=None):
        """Device rod specs (P12) from the .rod table."""
        from ibamr_tpu.ops import rods as rods_mod
        import jax.numpy as jnp

        if self.rods is None or not len(self.rods):
            return None
        if dtype is None:
            dtype = jnp.float32
        r = self.rods
        off = self.index_offset
        return rods_mod.make_rods(
            r[:, 0].astype(np.int32) + off,
            r[:, 1].astype(np.int32) + off,
            b=r[:, 3:6], s=r[:, 6:9], kappa=r[:, 9:12],
            ds=r[:, 2], dtype=dtype)

    def source_specs(self, dtype=None):
        """Device source specs (P14) from the .source table."""
        from ibamr_tpu.ops import sources as src_mod
        import jax.numpy as jnp

        if self.sources is None or not len(self.sources):
            return None
        if dtype is None:
            dtype = jnp.float32
        s = self.sources
        return src_mod.make_sources(
            s[:, 0].astype(np.int32) + self.index_offset, s[:, 1],
            dtype=dtype)

    def meter_specs(self, closed=True, dtype=None):
        """Instrument meters (P13) from the .inst table: group rows by
        meter index, order nodes within each meter by node index."""
        from ibamr_tpu import instruments
        import jax.numpy as jnp

        if self.inst is None or not len(self.inst):
            return None
        if dtype is None:
            dtype = jnp.float32
        tbl = self.inst
        loops = []
        for m in sorted(set(int(v) for v in tbl[:, 1])):
            rows = tbl[tbl[:, 1] == m]
            order = np.argsort(rows[:, 2])
            loops.append([int(v) + self.index_offset
                          for v in rows[order, 0]])
        return instruments.make_meters(loops, closed=closed, dtype=dtype)

    def mass_arrays(self, dtype=np.float64):
        """(mass(N,), penalty_stiffness(N,)) dense arrays for the
        penalty-IB integrator (P14) from the .mass table."""
        if self.masses is None or not len(self.masses):
            return None
        N = self.num_markers
        mass = np.zeros(N, dtype=dtype)
        kappa = np.zeros(N, dtype=dtype)
        m = self.masses
        idx = m[:, 0].astype(np.int64)
        mass[idx] = m[:, 1]
        kappa[idx] = m[:, 2] if m.shape[1] > 2 else 0.0
        return mass, kappa

    def anchors_to_targets(self, stiffness: float) -> None:
        """Realize anchored nodes (.anchor) as stiff target points at
        their initial positions, appended to the .target table — the
        fixed-point semantics of the reference's anchor nodes within
        the SoA force framework."""
        if self.anchors is None or not len(self.anchors):
            return
        rows = np.zeros((len(self.anchors), 2))
        rows[:, 0] = self.anchors[:, 0]
        rows[:, 1] = float(stiffness)
        self.anchors = None       # consume: repeated calls must not
        #                           stack duplicate pin springs
        if self.targets is None:
            self.targets = rows
        else:
            w = max(self.targets.shape[1], 2)
            old = np.zeros((len(self.targets), w))
            old[:, :self.targets.shape[1]] = self.targets
            new = np.zeros((len(rows), w))
            new[:, :2] = rows
            self.targets = np.concatenate([old, new])


def read_structure(basename: str, dim: Optional[int] = None) -> StructureData:
    """Read ``basename.vertex`` (+ optional .spring/.beam/.target)."""
    vpath = basename + ".vertex"
    if not os.path.exists(vpath):
        raise FileNotFoundError(vpath)
    verts = _read_table(vpath, 2, 3, "vertex")
    if dim is not None:
        verts = verts[:, :dim]
    data = StructureData(name=os.path.basename(basename), vertices=verts)
    d = verts.shape[1]
    if os.path.exists(basename + ".spring"):
        data.springs = _read_table(basename + ".spring", 4, 5, "spring")
    if os.path.exists(basename + ".beam"):
        data.beams = _read_table(basename + ".beam", 4, 4 + d, "beam")
    if os.path.exists(basename + ".target"):
        data.targets = _read_table(basename + ".target", 2, 3, "target")
    if os.path.exists(basename + ".rod"):
        data.rods = _read_table(basename + ".rod", 12, 12, "rod")
    if os.path.exists(basename + ".anchor"):
        data.anchors = _read_table(basename + ".anchor", 1, 1, "anchor")
    if os.path.exists(basename + ".mass"):
        data.masses = _read_table(basename + ".mass", 2, 3, "mass")
    if os.path.exists(basename + ".source"):
        data.sources = _read_table(basename + ".source", 2, 2, "source")
    if os.path.exists(basename + ".inst"):
        data.inst = _read_table(basename + ".inst", 3, 3, "inst")
    # index sanity across every table that names vertices
    n = verts.shape[0]
    for attr, ext, col in (
            ("springs", "spring", (0, 1)), ("beams", "beam", (0, 1, 2)),
            ("targets", "target", (0,)), ("rods", "rod", (0, 1)),
            ("anchors", "anchor", (0,)), ("masses", "mass", (0,)),
            ("sources", "source", (0,)), ("inst", "inst", (0,))):
        tbl = getattr(data, attr)
        if tbl is not None and len(tbl):
            for c in col:
                bad = (tbl[:, c] < 0) | (tbl[:, c] >= n)
                if bad.any():
                    raise ValueError(
                        f"{basename}.{ext}: vertex index out of range "
                        f"(N={n}) on entry {int(np.argmax(bad))}")
    return data


def write_structure(basename: str, data: StructureData) -> None:
    """Write the structure back out in the reference formats."""
    def _dump(path, arr, fmt):
        with open(path, "w") as f:
            f.write(f"{arr.shape[0]}\n")
            for row in arr:
                f.write(fmt(row) + "\n")

    _dump(basename + ".vertex", data.vertices,
          lambda r: " ".join(f"{v:.17g}" for v in r))
    if data.springs is not None:
        _dump(basename + ".spring", data.springs,
              lambda r: f"{int(r[0])} {int(r[1])} " +
              " ".join(f"{v:.17g}" for v in r[2:]))
    if data.beams is not None:
        _dump(basename + ".beam", data.beams,
              lambda r: f"{int(r[0])} {int(r[1])} {int(r[2])} " +
              " ".join(f"{v:.17g}" for v in r[3:]))
    if data.targets is not None:
        _dump(basename + ".target", data.targets,
              lambda r: f"{int(r[0])} " +
              " ".join(f"{v:.17g}" for v in r[1:]))
    if data.rods is not None:
        _dump(basename + ".rod", data.rods,
              lambda r: f"{int(r[0])} {int(r[1])} " +
              " ".join(f"{v:.17g}" for v in r[2:]))
    if data.anchors is not None:
        _dump(basename + ".anchor", data.anchors,
              lambda r: f"{int(r[0])}")
    if data.masses is not None:
        _dump(basename + ".mass", data.masses,
              lambda r: f"{int(r[0])} " +
              " ".join(f"{v:.17g}" for v in r[1:]))
    if data.sources is not None:
        _dump(basename + ".source", data.sources,
              lambda r: f"{int(r[0])} {r[1]:.17g}")
    if data.inst is not None:
        _dump(basename + ".inst", data.inst,
              lambda r: f"{int(r[0])} {int(r[1])} {int(r[2])}")
