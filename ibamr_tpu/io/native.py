"""ctypes binding for the native host-runtime library.

Reference parity: the reference's host runtime is C++ end to end
(SURVEY.md §2.5); the rebuild keeps the TPU compute path in JAX/XLA and
implements the host-side hot loops (structure-file parsing P10, binary
viz encoding T15) natively in C++ (``native/ibamr_native.cpp``),
bound via ctypes (no pybind11 in the image, per environment).

The library is compiled on demand with g++ and cached under
``native/build/``; every entry point has a NumPy fallback so the
framework works (slower) on machines without a toolchain.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional, Tuple

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
_SRC = os.path.join(_REPO, "native", "ibamr_native.cpp")
_BUILD_DIR = os.path.join(_REPO, "native", "build")
_LIB_PATH = os.path.join(_BUILD_DIR, "libibamr_native.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _compile() -> Optional[str]:
    os.makedirs(_BUILD_DIR, exist_ok=True)
    cmd = ["g++", "-O3", "-shared", "-fPIC", _SRC, "-o", _LIB_PATH]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        return _LIB_PATH
    except (OSError, subprocess.SubprocessError):
        return None


def get_lib() -> Optional[ctypes.CDLL]:
    """Load (compiling if needed) the native library; None if
    unavailable — callers fall back to NumPy."""
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        stale = (os.path.exists(_LIB_PATH) and os.path.exists(_SRC)
                 and os.path.getmtime(_SRC) > os.path.getmtime(_LIB_PATH))
        path = (_LIB_PATH if os.path.exists(_LIB_PATH) and not stale
                else (_compile() if os.path.exists(_SRC) else None))
        if path is None:
            return None
        _lib = _load(path)
        if _lib is None and os.path.exists(_SRC):
            # stale cached .so with a different ABI (mtimes can lie after
            # checkouts, ADVICE round 1): rebuild once and retry
            if _compile() is not None:
                _lib = _load(_LIB_PATH)
        return _lib


def _load(path: str) -> Optional[ctypes.CDLL]:
    """Load + ABI-check + declare signatures; None on any mismatch
    (missing symbols raise AttributeError, not just OSError)."""
    try:
        lib = ctypes.CDLL(path)
        if lib.ibamr_native_abi_version() != 2:
            return None
        lib.parse_table.restype = ctypes.c_long
        lib.parse_table.argtypes = [
            ctypes.c_char_p, ctypes.c_long,
            ctypes.POINTER(ctypes.c_double), ctypes.c_long,
            ctypes.c_long, ctypes.POINTER(ctypes.c_int),
            ctypes.POINTER(ctypes.c_long)]
        lib.encode_base64.restype = ctypes.c_long
        lib.encode_base64.argtypes = [
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_long,
            ctypes.c_char_p]
        return lib
    except (OSError, AttributeError):
        return None


def parse_table_native(text: bytes, max_cols: int
                       ) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """Parse a numeric table with the C++ tokenizer -> (rows, ncols);
    None if the native library is unavailable. ``ncols`` holds the TRUE
    per-row column counts (callers validate bounds). Raises ValueError
    on an invalid token (strict, matching the Python parser)."""
    lib = get_lib()
    if lib is None:
        return None
    # upper bound on rows: number of newlines + 1
    max_rows = text.count(b"\n") + 1
    out = np.empty((max_rows, max_cols), dtype=np.float64)
    ncols = np.zeros(max_rows, dtype=np.int32)
    status = ctypes.c_long(0)
    n = lib.parse_table(
        text, len(text),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        max_rows, max_cols,
        ncols.ctypes.data_as(ctypes.POINTER(ctypes.c_int)),
        ctypes.byref(status))
    if status.value != 0:
        raise ValueError(
            f"invalid numeric token on line {status.value}")
    return out[:n], ncols[:n]


def base64_native(data: bytes) -> Optional[bytes]:
    """RFC 4648 base64 via the C++ encoder; None if unavailable."""
    lib = get_lib()
    if lib is None:
        return None
    n = len(data)
    out = ctypes.create_string_buffer(4 * ((n + 2) // 3))
    arr = (ctypes.c_uint8 * n).from_buffer_copy(data)
    m = lib.encode_base64(arr, n, out)
    return out.raw[:m]
