from ibamr_tpu.io.structures import (
    StructureData, read_structure, write_structure)

__all__ = ["StructureData", "read_structure", "write_structure"]
