"""VTK XML writers: Eulerian grid fields and Lagrangian marker/fiber data.

Reference parity: the visualization pipeline (T15 + SAMRAI's
``VisItDataWriter``, SURVEY.md §5.5) — the reference dumps SAMRAI plot
files for VisIt plus SILO fiber files (``LSiloDataWriter``). The rebuild
writes standard VTK XML (dependency-free ASCII): ``.vti`` ImageData for
cell/face fields, ``.vtp`` PolyData for markers and fiber polylines, and
a ``.pvd`` collection indexing the time series — loadable by ParaView
and VisIt alike.

Host-side IO only (arrays are pulled off-device once per dump cadence,
the analog of the reference's viz_dump_interval).
"""

from __future__ import annotations

import os
from typing import Dict, Optional, Sequence

import numpy as np

from ibamr_tpu.grid import StaggeredGrid


def _ascii(flat: np.ndarray) -> str:
    """Float32-precision ascii payload (callers pass data pre-raveled
    in the required order)."""
    return " ".join(f"{v:.7g}" for v in np.asarray(flat).ravel())


def _b64(data: bytes) -> str:
    """Base64 via the native C++ encoder (io.native) with a stdlib
    fallback — the binary-payload hot loop for large dumps."""
    from ibamr_tpu.io.native import base64_native
    out = base64_native(data)
    if out is None:
        import base64
        out = base64.b64encode(data)
    return out.decode("ascii")


def _binary_payload(arr: np.ndarray) -> str:
    """VTK inline-binary DataArray payload: uint32 byte count header +
    raw little-endian data, base64 encoded."""
    raw = np.ascontiguousarray(arr, dtype=np.float32).tobytes()
    head = np.uint32(len(raw)).tobytes()
    return _b64(head + raw)


def write_vti(path: str, grid: StaggeredGrid,
              cell_fields: Optional[Dict[str, np.ndarray]] = None,
              fmt: str = "ascii") -> str:
    """Write cell-centered fields on the uniform grid as VTK ImageData.

    Vector fields may be passed as tuples/stacked (dim, *n) arrays —
    written as 3-component vectors (zero-padded in 2D).
    ``fmt``: "ascii" (diff-friendly) or "binary" (inline base64 via the
    native encoder — use for large grids).
    """
    if fmt not in ("ascii", "binary"):
        raise ValueError(f"unknown vti format {fmt!r}")
    cell_fields = cell_fields or {}
    dim = grid.dim
    n = tuple(grid.n) + (1,) * (3 - dim)
    dx = tuple(grid.dx) + (1.0,) * (3 - dim)
    x0 = tuple(grid.x_lo) + (0.0,) * (3 - dim)

    def emit(parts, flat, name, ncomp):
        comp_attr = (f'NumberOfComponents="{ncomp}" ' if ncomp > 1 else "")
        parts.append(f'        <DataArray type="Float32" Name="{name}" '
                     f'{comp_attr}format="{fmt}">\n')
        if fmt == "ascii":
            parts.append(_ascii(flat))
        else:
            parts.append(_binary_payload(flat))
        parts.append('\n        </DataArray>\n')

    parts = []
    parts.append('<?xml version="1.0"?>\n')
    parts.append('<VTKFile type="ImageData" version="0.1" '
                 'byte_order="LittleEndian" header_type="UInt32">\n')
    parts.append(f'  <ImageData WholeExtent="0 {n[0]} 0 {n[1]} 0 {n[2]}" '
                 f'Origin="{x0[0]} {x0[1]} {x0[2]}" '
                 f'Spacing="{dx[0]} {dx[1]} {dx[2]}">\n')
    parts.append(f'    <Piece Extent="0 {n[0]} 0 {n[1]} 0 {n[2]}">\n')
    parts.append('      <CellData>\n')
    for name, arr in cell_fields.items():
        a = np.asarray(arr)
        if isinstance(arr, (tuple, list)) or a.ndim == dim + 1:
            comps = [np.asarray(c) for c in arr] if isinstance(
                arr, (tuple, list)) else [a[d] for d in range(a.shape[0])]
            while len(comps) < 3:
                comps.append(np.zeros_like(comps[0]))
            vec = np.stack([c.ravel(order="F") for c in comps], axis=1)
            emit(parts, vec, name, 3)
        else:
            emit(parts, a.ravel(order="F"), name, 1)
    parts.append('      </CellData>\n')
    parts.append('    </Piece>\n  </ImageData>\n</VTKFile>\n')
    with open(path, "w") as f:
        f.write("".join(parts))
    return path


def write_vtp(path: str, X: np.ndarray,
              point_data: Optional[Dict[str, np.ndarray]] = None,
              lines: Optional[Sequence[Sequence[int]]] = None) -> str:
    """Write markers (and optional fiber polylines) as VTK PolyData.

    X: (N, dim) positions (zero-padded to 3D); point_data: per-marker
    scalars/vectors; lines: index chains rendered as polylines (the
    LSiloDataWriter fiber analog).
    """
    X = np.asarray(X, dtype=np.float64)
    N, dim = X.shape
    if dim < 3:
        X = np.concatenate([X, np.zeros((N, 3 - dim))], axis=1)
    point_data = point_data or {}
    lines = lines or []

    parts = []
    parts.append('<?xml version="1.0"?>\n')
    parts.append('<VTKFile type="PolyData" version="0.1" '
                 'byte_order="LittleEndian">\n  <PolyData>\n')
    n_verts = 0 if lines else N
    parts.append(f'    <Piece NumberOfPoints="{N}" NumberOfVerts="{n_verts}" '
                 f'NumberOfLines="{len(lines)}" NumberOfStrips="0" '
                 'NumberOfPolys="0">\n')
    parts.append('      <Points>\n        <DataArray type="Float32" '
                 'NumberOfComponents="3" format="ascii">\n')
    parts.append(_ascii(X))
    parts.append('\n        </DataArray>\n      </Points>\n')

    parts.append('      <PointData>\n')
    for name, arr in point_data.items():
        a = np.asarray(arr, dtype=np.float64)
        if a.ndim == 2:
            if a.shape[1] < 3:
                a = np.concatenate(
                    [a, np.zeros((a.shape[0], 3 - a.shape[1]))], axis=1)
            parts.append(f'        <DataArray type="Float32" Name="{name}" '
                         'NumberOfComponents="3" format="ascii">\n')
        else:
            parts.append(f'        <DataArray type="Float32" Name="{name}" '
                         'format="ascii">\n')
        parts.append(_ascii(a))
        parts.append('\n        </DataArray>\n')
    parts.append('      </PointData>\n')

    if lines:
        conn = []
        offs = []
        total = 0
        for chain in lines:
            conn.extend(int(i) for i in chain)
            total += len(chain)
            offs.append(total)
        parts.append('      <Lines>\n        <DataArray type="Int32" '
                     'Name="connectivity" format="ascii">\n')
        parts.append(" ".join(str(i) for i in conn))
        parts.append('\n        </DataArray>\n        <DataArray '
                     'type="Int32" Name="offsets" format="ascii">\n')
        parts.append(" ".join(str(i) for i in offs))
        parts.append('\n        </DataArray>\n      </Lines>\n')
    else:
        parts.append('      <Verts>\n        <DataArray type="Int32" '
                     'Name="connectivity" format="ascii">\n')
        parts.append(" ".join(str(i) for i in range(N)))
        parts.append('\n        </DataArray>\n        <DataArray '
                     'type="Int32" Name="offsets" format="ascii">\n')
        parts.append(" ".join(str(i + 1) for i in range(N)))
        parts.append('\n        </DataArray>\n      </Verts>\n')

    parts.append('    </Piece>\n  </PolyData>\n</VTKFile>\n')
    with open(path, "w") as f:
        f.write("".join(parts))
    return path


class VizWriter:
    """Time-series dump manager (the VisItDataWriter/viz_dump_interval
    analog): collects per-step .vti/.vtp files under ``viz_dir`` and
    maintains .pvd collection indexes ParaView opens directly."""

    def __init__(self, viz_dir: str, grid: StaggeredGrid):
        self.viz_dir = viz_dir
        self.grid = grid
        os.makedirs(viz_dir, exist_ok=True)
        self._eul: list = []
        self._lag: list = []
        self._amr: list = []

    def dump(self, step: int, t: float,
             cell_fields: Optional[Dict] = None,
             markers: Optional[np.ndarray] = None,
             marker_data: Optional[Dict] = None,
             fibers: Optional[Sequence[Sequence[int]]] = None) -> None:
        if cell_fields:
            fname = f"eul_{step:06d}.vti"
            write_vti(os.path.join(self.viz_dir, fname), self.grid,
                      {k: np.asarray(v) if not isinstance(v, (tuple, list))
                       else tuple(np.asarray(c) for c in v)
                       for k, v in cell_fields.items()})
            self._eul.append((t, fname))
        if markers is not None:
            fname = f"lag_{step:06d}.vtp"
            write_vtp(os.path.join(self.viz_dir, fname),
                      np.asarray(markers),
                      point_data={k: np.asarray(v) for k, v in
                                  (marker_data or {}).items()},
                      lines=fibers)
            self._lag.append((t, fname))
        self._write_pvd()

    def dump_hierarchy(self, step: int, t: float, level_grids,
                       level_fields, fmt: str = "ascii") -> None:
        """AMR time-series dump: a .vtm multiblock (one ImageData per
        level) per step, indexed by hierarchy.pvd."""
        fname = f"amr_{step:06d}.vtm"
        write_vtm_hierarchy(os.path.join(self.viz_dir, fname),
                            level_grids, level_fields, fmt=fmt)
        self._amr.append((t, fname))
        self._write_pvd()

    def _write_pvd(self) -> None:
        for series, name in ((self._eul, "eulerian.pvd"),
                             (self._lag, "lagrangian.pvd"),
                             (self._amr, "hierarchy.pvd")):
            if not series:
                continue
            rows = "\n".join(
                f'    <DataSet timestep="{t}" file="{f}"/>'
                for t, f in series)
            body = ('<?xml version="1.0"?>\n<VTKFile type="Collection" '
                    'version="0.1">\n  <Collection>\n'
                    + rows + '\n  </Collection>\n</VTKFile>\n')
            with open(os.path.join(self.viz_dir, name), "w") as f:
                f.write(body)


def write_vtm_hierarchy(path: str, level_grids, level_fields,
                        fmt: str = "ascii") -> str:
    """AMR hierarchy dump: one ``.vti`` ImageData per level (each with
    its own origin/spacing — the refined boxes are their own uniform
    grids) referenced from a ``.vtm`` vtkMultiBlockDataSet index that
    ParaView/VisIt open directly. The reference dumps its patch
    hierarchy through VisItDataWriter the same one-file-per-level way
    (SURVEY.md §5.5 [U]).

    ``level_grids``: sequence of :class:`StaggeredGrid` (level 0 the
    root; finer levels e.g. ``box.fine_grid(parent)`` /
    ``LevelSpec.grid``). ``level_fields``: per-level dict for
    :func:`write_vti`.
    """
    if len(level_grids) != len(level_fields):
        raise ValueError(
            f"{len(level_grids)} level grids vs {len(level_fields)} "
            "field dicts — a level would be silently dropped")
    base = os.path.splitext(os.path.basename(path))[0]
    d = os.path.dirname(path) or "."
    os.makedirs(d, exist_ok=True)
    rows = []
    for l, (g, fields) in enumerate(zip(level_grids, level_fields)):
        fname = f"{base}_L{l}.vti"
        write_vti(os.path.join(d, fname), g, fields, fmt=fmt)
        rows.append(f'    <Block index="{l}" name="level_{l}">\n'
                    f'      <DataSet index="0" file="{fname}"/>\n'
                    f'    </Block>')
    body = ('<?xml version="1.0"?>\n'
            '<VTKFile type="vtkMultiBlockDataSet" version="1.0" '
            'byte_order="LittleEndian">\n'
            '  <vtkMultiBlockDataSet>\n'
            + "\n".join(rows)
            + '\n  </vtkMultiBlockDataSet>\n</VTKFile>\n')
    with open(path, "w") as f:
        f.write(body)
    return path


# VTK cell-type ids for the FE element menu. Node orderings: VTK's
# quadratic simplices list corners then edge midpoints over the same
# edge sets as fe/fem.py's libMesh-order tables ((0,1),(1,2),(2,0)[,(0,3),
# (1,3),(2,3)]) — midpoints are direction-free, so connectivity passes
# through unchanged; QUAD4/HEX8 counterclockwise/bottom-top orders also
# coincide.
_VTK_CELL_TYPES = {
    "TRI3": 5,
    "QUAD4": 9,
    "TET4": 10,
    "HEX8": 12,
    "TRI6": 22,
    "TET10": 24,
}


def write_vtu(path: str, nodes: np.ndarray, elems: np.ndarray,
              elem_type: str,
              point_data: Optional[Dict[str, np.ndarray]] = None) -> str:
    """Write an FE mesh (current or reference configuration) as VTK
    UnstructuredGrid — the IBFE structure-viz analog of the reference's
    libMesh Exodus output (SURVEY.md T15/T16): ParaView renders the
    deformed solid with its real element connectivity, not just a
    marker cloud. ``point_data``: per-node scalars/vectors (zero-padded
    to 3 components)."""
    if elem_type not in _VTK_CELL_TYPES:
        raise ValueError(f"unsupported element type {elem_type!r} "
                         f"(menu: {sorted(_VTK_CELL_TYPES)})")
    nodes = np.asarray(nodes, dtype=np.float64)
    elems = np.asarray(elems, dtype=np.int64)
    N, dim = nodes.shape
    if dim < 3:
        nodes = np.concatenate([nodes, np.zeros((N, 3 - dim))], axis=1)
    E, nen = elems.shape
    ctype = _VTK_CELL_TYPES[elem_type]
    point_data = point_data or {}

    parts = ['<?xml version="1.0"?>\n',
             '<VTKFile type="UnstructuredGrid" version="0.1" '
             'byte_order="LittleEndian">\n  <UnstructuredGrid>\n',
             f'    <Piece NumberOfPoints="{N}" NumberOfCells="{E}">\n',
             '      <Points>\n        <DataArray type="Float32" '
             'NumberOfComponents="3" format="ascii">\n',
             _ascii(nodes.reshape(-1)),
             '\n        </DataArray>\n      </Points>\n',
             '      <Cells>\n        <DataArray type="Int64" '
             'Name="connectivity" format="ascii">\n',
             " ".join(str(v) for v in elems.reshape(-1)),
             '\n        </DataArray>\n        <DataArray type="Int64" '
             'Name="offsets" format="ascii">\n',
             " ".join(str(nen * (e + 1)) for e in range(E)),
             '\n        </DataArray>\n        <DataArray type="UInt8" '
             'Name="types" format="ascii">\n',
             " ".join(str(ctype) for _ in range(E)),
             '\n        </DataArray>\n      </Cells>\n']
    if point_data:
        parts.append('      <PointData>\n')
        for name, arr in point_data.items():
            a = np.asarray(arr, dtype=np.float64)
            if a.ndim == 1:
                ncomp = 1
                flat = a
            else:
                if a.shape[1] < 3:
                    a = np.concatenate(
                        [a, np.zeros((a.shape[0], 3 - a.shape[1]))],
                        axis=1)
                ncomp = a.shape[1]
                flat = a.reshape(-1)
            parts.append(f'        <DataArray type="Float32" '
                         f'Name="{name}" NumberOfComponents="{ncomp}" '
                         'format="ascii">\n')
            parts.append(_ascii(flat))
            parts.append('\n        </DataArray>\n')
        parts.append('      </PointData>\n')
    parts.append('    </Piece>\n  </UnstructuredGrid>\n</VTKFile>\n')
    with open(path, "w") as f:
        f.write("".join(parts))
    return path
