"""Two-level composite incompressible Navier-Stokes (+ IB coupling).

Reference parity: the reason IBAMR exists — running the INS solve on a
locally-refined hierarchy around the immersed structure (SURVEY.md §0,
§5.7; P2/P8 over T10/S4). Round 1 had all the coarse-fine machinery
(amr.py) but only ever advanced a passive scalar with it (VERDICT round
1 item 4); this module runs the FLUID on a composite two-level grid.

Scheme (one static FineBox, refinement ratio 2, shared dt):

1. explicit convective + viscous RHS per level — the fine box works on
   ghost-extended arrays whose ghost shell is quadratically interpolated
   from the coarse level at MAC positions (T10 CF interpolation);
2. slave the covered coarse region to the restriction of the fine
   predictor (coincident-face mean restriction, flux preserving);
3. **composite projection**: one FGMRES solve over the pytree
   (phi_coarse, phi_fine) of the true composite Poisson operator —
   covered coarse cells carry the slaving identity
   ``phi_c - restrict(phi_f) = 0``, uncovered cells the usual 5/7-point
   Laplacian with the coarse flux through each coarse-fine interface
   face REPLACED by the transverse mean of the fine-side fluxes (the
   CoarsenSchedule flux-synchronization contract), and fine cells the
   box Laplacian with CF-interpolated ghosts. Preconditioner = exact
   periodic FFT inverse (coarse) + fast-diagonalization Dirichlet
   inverse (fine box) — the FAC V-cycle collapsed to its two-level
   exact-solver limit (SURVEY.md §3.3 TPU note);
4. correct both levels with consistent gradients and synchronize
   (covered coarse faces := restricted fine faces).

After the solve the composite divergence vanishes to solver tolerance
on fine interior cells AND uncovered coarse cells including the ring
adjacent to the interface — the property the tests enforce.

The IB coupling (``TwoLevelIBINS``) keeps the structure inside the fine
box — the reference's canonical usage (refine around the structure):
spread at FINE resolution only, restrict the force to the coarse level,
interpolate marker velocities from the fine level.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from ibamr_tpu.amr import (FineBox, _box_mac_divergence, fill_fine_ghosts,
                           interp_periodic, prolong_mac_div_preserving,
                           restrict_cc, restrict_mac)
from ibamr_tpu.bc import DomainBC, dirichlet_axis
from ibamr_tpu.grid import StaggeredGrid
from ibamr_tpu.ops import stencils
from ibamr_tpu.ops.convection import convective_rate
from ibamr_tpu.solvers import fft
from ibamr_tpu.solvers.fastdiag import FastDiagSolver
from ibamr_tpu.solvers.krylov import fgmres

Vel = Tuple[jnp.ndarray, ...]


# --------------------------------------------------------------------------
# box-local MAC helpers (component d has shape fine_n + e_d)
# --------------------------------------------------------------------------

def _shift(a, axis, s, n):
    idx = [slice(None)] * a.ndim
    idx[axis] = slice(s, s + n)
    return a[tuple(idx)]


def fill_fine_ghosts_mac(uf: Vel, uc: Vel, box: FineBox,
                         ghost: int) -> Vel:
    """Ghost-extend fine-box MAC components with quadratic CF
    interpolation of the coarse MAC field at the fine face positions
    (the side-centered twin of amr.fill_fine_ghosts)."""
    dim = box.dim
    g = ghost
    r = box.ratio
    out = []
    for d in range(dim):
        shp = tuple(box.fine_n[a] + (1 if a == d else 0) + 2 * g
                    for a in range(dim))
        ext = jnp.zeros(shp, dtype=uf[d].dtype)
        inner = tuple(slice(g, g + box.fine_n[a] + (1 if a == d else 0))
                      for a in range(dim))
        ext = ext.at[inner].set(uf[d])
        # interpolate the whole extended array from coarse, then put the
        # interior back (the ghost shell is O(surface); interpolating the
        # full box keeps the code simple and the interior is overwritten)
        axes = []
        for a in range(dim):
            i = jnp.arange(-g, box.fine_n[a] + (1 if a == d else 0) + g,
                           dtype=uc[d].dtype)
            if a == d:
                # fine face i sits at coarse FACE index lo + i/r
                axes.append(box.lo[a] + i / r)
            else:
                # fine cell center -> coarse cell-center index coords
                axes.append(box.lo[a] + (i + 0.5) / r - 0.5)
        pts = jnp.stack(jnp.meshgrid(*axes, indexing="ij"), axis=-1)
        full = interp_periodic(uc[d], pts, order=2)
        ext = full.at[inner].set(uf[d])
        out.append(ext)
    return tuple(out)


def box_strain_magnitude(uext: Vel, dx_f, g: int, fine_n):
    """|S| = sqrt(2 E:E) at cell centers of a ghost-extended box MAC
    field, keeping a (g-1)-deep ghost shell (diagonal strain needs one
    face beyond the cell; off-diagonals one cell of each neighbor).
    The cell-centered twin of ops.stencils.strain_rate_cc on the
    face-complete box layout — input ghosts g, output ghosts g-1."""
    dim = len(uext)
    go = g - 1
    cells = tuple(n + 2 * go for n in fine_n)

    # cell-averaged components (for off-diagonal centered differences)
    ucc = []
    for d in range(dim):
        c = uext[d]
        lo = [slice(None)] * dim
        hi = [slice(None)] * dim
        lo[d] = slice(0, -1)
        hi[d] = slice(1, None)
        ucc.append(0.5 * (c[tuple(lo)] + c[tuple(hi)]))   # ghosts g
    acc = None
    for i in range(dim):
        # exact MAC diagonal: faces bounding the cell
        lo = [slice(None)] * dim
        hi = [slice(None)] * dim
        lo[i] = slice(0, -1)
        hi[i] = slice(1, None)
        Eii = (uext[i][tuple(hi)] - uext[i][tuple(lo)]) / dx_f[i]
        Eii = Eii[tuple(slice(g - go, g - go + cells[a])
                        for a in range(dim))]
        t = Eii * Eii
        acc = t if acc is None else acc + t
        for j in range(i + 1, dim):
            def dcc(f, ax):
                lo2 = [slice(None)] * dim
                hi2 = [slice(None)] * dim
                lo2[ax] = slice(0, -2)
                hi2[ax] = slice(2, None)
                return (f[tuple(hi2)] - f[tuple(lo2)]) \
                    / (2.0 * dx_f[ax])

            a1 = dcc(ucc[i], j)     # ghosts g, minus 1 on axis j
            a2 = dcc(ucc[j], i)     # ghosts g, minus 1 on axis i
            # crop both to the common (g-1)-ghost cell window
            def crop_mixed(a, lost_ax):
                sl = []
                for ax in range(dim):
                    base = g - 1 if ax == lost_ax else g
                    sl.append(slice(base - go, base - go + cells[ax]))
                return a[tuple(sl)]

            Eij = 0.5 * (crop_mixed(a1, j) + crop_mixed(a2, i))
            acc = acc + 2.0 * Eij * Eij
    return jnp.sqrt(2.0 * acc)


def box_eddy_viscous_force(uext: Vel, mu_ext, dx_f, g: int, fine_n):
    """div(2 mu D(u)) on the ghost-extended box MAC layout — the fine-
    level twin of INSVCStaggeredIntegrator._viscous_force (periodic
    rolls there, explicit slices here). ``mu_ext`` is cell-centered
    with ``g-1`` ghosts (box_strain_magnitude's output shell); the
    result is interior box MAC components (shape fine_n + e_d). Needs
    g >= 3 so every stencil stays inside valid ghosts."""
    dim = len(uext)
    gm = g - 1                              # mu ghost depth

    def face_crop(a, d, offs):
        """Crop array ``a`` whose axis offsets (vs the interior box
        face array of component d) are ``offs[ax]`` ghost layers."""
        out = []
        for ax in range(dim):
            n = fine_n[ax] + (1 if ax == d else 0)
            out.append(slice(offs[ax], offs[ax] + n))
        return a[tuple(out)]

    forces = []
    for d in range(dim):
        acc = None
        for j in range(dim):
            if j == d:
                # tau_dd = 2 mu du_d/dx_d at cells (mu ghosts gm)
                lo = [slice(None)] * dim
                hi = [slice(None)] * dim
                lo[d] = slice(0, -1)
                hi[d] = slice(1, None)
                dudd = (uext[d][tuple(hi)] - uext[d][tuple(lo)]) \
                    / dx_f[d]               # cell-like, ghosts g
                # align mu (ghosts gm) with dudd (ghosts g)
                sl = tuple(slice(g - gm, g - gm + fine_n[a] + 2 * gm)
                           for a in range(dim))
                tau = 2.0 * mu_ext * dudd[sl]     # ghosts gm
                lo2 = [slice(None)] * dim
                hi2 = [slice(None)] * dim
                lo2[d] = slice(0, -1)
                hi2[d] = slice(1, None)
                dtau = (tau[tuple(hi2)] - tau[tuple(lo2)]) / dx_f[d]
                # dtau: faces along d with gm-1 offset... face k uses
                # cells k-1,k -> face array ghosts gm on transverse,
                # gm - ? along d: entries = n_d + 2gm - 1 faces,
                # interior faces n_d + 1 -> offset gm - 1
                offs = [gm] * dim
                offs[d] = gm - 1
                term = face_crop(dtau, d, offs)
            else:
                # tau_dj at (d, j) corners: mu corner-averaged.
                # Raw central differences (corner-positioned):
                #   dudj: diff of u_d (face-complete on d) along j
                #   dujd: diff of u_j (face-complete on j) along d
                # Corner (kd, kj) lives at entry kd+g on a face-kept
                # axis and kd+g-1 on the diffed axis; both are aligned
                # to mu's corner window (corners 1-gm .. n+gm-1 on the
                # d/j axes, cells with gm ghosts elsewhere).
                lo = [slice(None)] * dim
                hi = [slice(None)] * dim
                lo[j] = slice(0, -1)
                hi[j] = slice(1, None)
                dudj = (uext[d][tuple(hi)] - uext[d][tuple(lo)]) \
                    / dx_f[j]
                lo2 = [slice(None)] * dim
                hi2 = [slice(None)] * dim
                lo2[d] = slice(0, -1)
                hi2[d] = slice(1, None)
                dujd = (uext[j][tuple(hi2)] - uext[j][tuple(lo2)]) \
                    / dx_f[d]

                def align(a, diffed_ax, kept_ax):
                    sl = []
                    for ax in range(dim):
                        if ax == diffed_ax:
                            start = g - gm
                            want = fine_n[ax] + 2 * gm - 1
                        elif ax == kept_ax:
                            start = g - gm + 1
                            want = fine_n[ax] + 2 * gm - 1
                        else:
                            start = g - gm
                            want = fine_n[ax] + 2 * gm
                        sl.append(slice(start, start + want))
                    return a[tuple(sl)]

                # mu at corners: average the 4 cells around the (d, j)
                # corner; mu_ext ghosts gm -> corner extent
                # n_ax + 2gm - 1 on d and j
                m = mu_ext
                for ax in (d, j):
                    lo3 = [slice(None)] * dim
                    hi3 = [slice(None)] * dim
                    lo3[ax] = slice(0, -1)
                    hi3[ax] = slice(1, None)
                    m = 0.5 * (m[tuple(lo3)] + m[tuple(hi3)])
                tau = m * (align(dudj, j, d) + align(dujd, d, j))
                # term = dtau/dx_j at d-faces: diff along j of the
                # corner array -> d-face-like
                lo4 = [slice(None)] * dim
                hi4 = [slice(None)] * dim
                lo4[j] = slice(0, -1)
                hi4[j] = slice(1, None)
                dtau = (tau[tuple(hi4)] - tau[tuple(lo4)]) / dx_f[j]
                # dtau extents: d: n_d + 2gm - 1 (corner count along
                # d = faces), j: n_j + 2gm - 2 cells, others n + 2gm.
                # interior: d faces n_d + 1 -> offset gm - 1; j cells
                # n_j -> offset gm - 1; others offset gm
                offs = [gm] * dim
                offs[d] = gm - 1
                offs[j] = gm - 1
                term = face_crop(dtau, d, offs)
            acc = term if acc is None else acc + term
        forces.append(acc)
    return tuple(forces)


def _box_convective_rate(uext: Vel, dx_f, g: int, fine_n) -> Vel:
    """Centered conservative N(u)_d on ghost-extended box MAC arrays;
    returns component d at its own faces (shape fine_n + e_d). Same
    arithmetic as ops.convection.convective_rate, box layout."""
    dim = len(uext)
    out = []
    for d in range(dim):
        nd = tuple(fine_n[a] + (1 if a == d else 0) for a in range(dim))
        acc = jnp.zeros(nd, dtype=uext[d].dtype)
        for e in range(dim):
            if e == d:
                # flux at cell centers along d (centers -1 .. n relative
                # to the stored faces 0..n): face j's divergence needs
                # centers j-1 and j, so n+2 centers from ghost faces
                ncent = fine_n[d] + 2
                a0 = _shift(uext[d], d, g - 1, ncent)   # faces -1..n
                a1 = _shift(uext[d], d, g, ncent)       # faces 0..n+1
                for a in range(dim):
                    if a != d:
                        a0 = _shift(a0, a, g, fine_n[a])
                        a1 = _shift(a1, a, g, fine_n[a])
                adv = 0.5 * (a0 + a1)
                flux = adv * adv
                acc = acc + (_shift(flux, d, 1, nd[d])
                             - _shift(flux, d, 0, nd[d])) / dx_f[d]
            else:
                # edge fluxes at (lower d-face, lower e-face)
                # adv = u_e averaged along d to the edge; edges j_e in
                # [0, fine_n[e]] (one extra), faces i_d in [0, fine_n[d]]
                ue = uext[e]
                b0 = _shift(ue, d, g - 1, nd[d])
                b1 = _shift(ue, d, g, nd[d])
                for a in range(dim):
                    if a == e:
                        b0 = _shift(b0, a, g, fine_n[e] + 1)
                        b1 = _shift(b1, a, g, fine_n[e] + 1)
                    elif a != d:
                        b0 = _shift(b0, a, g, fine_n[a])
                        b1 = _shift(b1, a, g, fine_n[a])
                adv = 0.5 * (b0 + b1)
                ud = uext[d]
                q0 = _shift(ud, e, g - 1, fine_n[e] + 1)
                q1 = _shift(ud, e, g, fine_n[e] + 1)
                for a in range(dim):
                    if a == d:
                        q0 = _shift(q0, a, g, nd[d])
                        q1 = _shift(q1, a, g, nd[d])
                    elif a != e:
                        q0 = _shift(q0, a, g, fine_n[a])
                        q1 = _shift(q1, a, g, fine_n[a])
                q = 0.5 * (q0 + q1)
                flux = adv * q                  # (.., nd[d] on d, ne+1 on e)
                acc = acc + (_shift(flux, e, 1, fine_n[e])
                             - _shift(flux, e, 0, fine_n[e])) / dx_f[e]
        out.append(acc)
    return tuple(out)


def _box_laplacian(uext: Vel, dx_f, g: int, fine_n) -> Vel:
    """Component Laplacians on ghost-extended box MAC arrays."""
    dim = len(uext)
    out = []
    for d in range(dim):
        nd = tuple(fine_n[a] + (1 if a == d else 0) for a in range(dim))
        c = uext[d]
        center = c
        for a in range(dim):
            center = _shift(center, a, g, nd[a])
        acc = jnp.zeros_like(center)
        for a in range(dim):
            lo = c
            hi = c
            for b in range(dim):
                lo = _shift(lo, b, g - (1 if b == a else 0), nd[b])
                hi = _shift(hi, b, g + (1 if b == a else 0), nd[b])
            acc = acc + (hi - 2.0 * center + lo) / dx_f[a] ** 2
        out.append(acc)
    return tuple(out)


def _box_cc_laplacian(phi_ext: jnp.ndarray, dx_f, fine_n) -> jnp.ndarray:
    """5/7-point Laplacian of a 1-ghost-extended box cell array."""
    dim = phi_ext.ndim
    center = phi_ext[tuple(slice(1, 1 + n) for n in fine_n)]
    acc = jnp.zeros_like(center)
    for a in range(dim):
        lo = phi_ext[tuple(slice(1 - (1 if b == a else 0),
                                 1 - (1 if b == a else 0) + fine_n[b])
                           for b in range(dim))]
        hi = phi_ext[tuple(slice(1 + (1 if b == a else 0),
                                 1 + (1 if b == a else 0) + fine_n[b])
                           for b in range(dim))]
        acc = acc + (hi - 2.0 * center + lo) / dx_f[a] ** 2
    return acc


def box_mac_gradient_correct(u_box: Vel, phi_ext: jnp.ndarray,
                             dx_f) -> Vel:
    """``u - grad(phi)`` on box MAC faces (complete-face layout) with
    gradients from the 1-ghost-extended cell array. Shared by the
    two-level and L-level composite projections."""
    dim = len(u_box)
    nf = tuple(s - 2 for s in phi_ext.shape)
    out = []
    for d in range(dim):
        lo = [slice(1, 1 + n) for n in nf]
        hi = [slice(1, 1 + n) for n in nf]
        lo[d] = slice(0, nf[d] + 1)
        hi[d] = slice(1, nf[d] + 2)
        g = (phi_ext[tuple(hi)] - phi_ext[tuple(lo)]) / dx_f[d]
        out.append(u_box[d] - g)
    return tuple(out)


def interface_flux_correction(lap_c, phi_eff, phi_ext, box: FineBox,
                              dx_c, dx_f):
    """Replace the parent flux through each CF interface face of ``box``
    by the restricted fine flux, adjusting the Laplacian of the OUTSIDE
    neighbor cells (the flux-sync rows). Works for any parent/child
    level pair of a nested hierarchy: ``lap_c``/``phi_eff`` are
    parent-level cell arrays (box coordinates index the parent),
    ``phi_ext`` is the 1-ghost-extended child array."""
    dim = lap_c.ndim
    r = box.ratio
    for d in range(dim):
        for side in (0, 1):
            # fine flux through the interface plane (outward = +-d)
            # fine cells: first interior layer vs ghost layer
            if side == 0:
                inner = 1
                ghostl = 0
                cout = box.lo[d] - 1      # outside parent cell
                cin = box.lo[d]
            else:
                inner = box.fine_n[d]
                ghostl = box.fine_n[d] + 1
                cout = box.hi[d]
                cin = box.hi[d] - 1
            sl_in = [slice(1, 1 + n) for n in box.fine_n]
            sl_gh = [slice(1, 1 + n) for n in box.fine_n]
            sl_in[d] = slice(inner, inner + 1)
            sl_gh[d] = slice(ghostl, ghostl + 1)
            # gradient at the interface: fine spacing between ghost
            # center and first interior center
            gf = (phi_ext[tuple(sl_gh)] - phi_ext[tuple(sl_in)]) \
                / dx_f[d]
            if side == 0:
                gf = -gf                  # make it the +d-face flux
            # transverse restriction: mean over fine face pairs
            gf = jnp.squeeze(gf, axis=d)
            tshape = []
            for a in range(dim):
                if a == d:
                    continue
                tshape += [box.shape[a], r]
            gf = gf.reshape(tshape)
            gf = gf.mean(axis=tuple(range(1, 2 * (dim - 1), 2)))
            gf = jnp.expand_dims(gf, axis=d)
            # parent flux lap_c already used through that face
            sl_out = [slice(box.lo[a], box.hi[a]) for a in range(dim)]
            sl_inn = [slice(box.lo[a], box.hi[a]) for a in range(dim)]
            sl_out[d] = slice(cout, cout + 1)
            sl_inn[d] = slice(cin, cin + 1)
            gc = (phi_eff[tuple(sl_inn)] - phi_eff[tuple(sl_out)]) \
                / dx_c[d]
            if side == 1:
                gc = -gc          # make gc the +d gradient (gf is
                #                   already +d-directed on both sides)
            # outside cell: the shared face is its UPPER face on the
            # lo side (+1/h) and its LOWER face on the hi side (-1/h)
            sgn = 1.0 if side == 0 else -1.0
            lap_c = lap_c.at[tuple(sl_out)].add(
                sgn * (gf - gc) / dx_c[d])
    return lap_c


# --------------------------------------------------------------------------
# composite projection
# --------------------------------------------------------------------------

class CompositeProjection:
    """FGMRES solve of the two-level composite Poisson problem (see
    module docstring), with velocity correction + interface sync."""

    def __init__(self, grid: StaggeredGrid, box: FineBox,
                 tol: float = 1e-9, m: int = 24, restarts: int = 8,
                 preconditioner=None):
        self.grid = grid
        self.box = box
        # optional external preconditioner (e.g. the FAC V-cycle of
        # ibamr_tpu.solvers.fac.FACCompositePoisson) replacing the
        # default FFT+fastdiag level-solver combination
        self._external_precond = preconditioner
        # convergence surfacing: eager projections record the inner
        # FGMRES stats here (and mirror them onto the FAC object when
        # ``preconditioner`` is its bound method) so metrics_fn/bench
        # can log convergence without re-running the solve
        self.last_solve_stats = None
        self.record_stats = False
        # GSPMD pins (parallel.mesh.make_sharded_two_level_ib_step):
        # coarse-level arrays pinned to the spatial sharding, fine-box
        # arrays pinned replicated, at EVERY level crossing — the
        # explicit-pin pattern that keeps the SPMD partitioner from
        # mis-propagating through the mixed scatter/gather composites
        # (same fix as make_sharded_multilevel_step; wrong values were
        # observed when left unconstrained). None = unsharded no-ops.
        self.level_sharding = None    # coarse arrays
        self.window_sharding = None   # fine-box arrays (replicated)
        self.dx = grid.dx
        self.dx_f = tuple(h / box.ratio for h in grid.dx)
        self.tol = float(tol)
        self.m = int(m)
        self.restarts = int(restarts)
        dim = grid.dim
        self.box_sl = tuple(slice(box.lo[a], box.hi[a])
                            for a in range(dim))
        covered = np.zeros(grid.n, dtype=bool)
        covered[tuple(np.s_[box.lo[a]:box.hi[a]] for a in range(dim))] = True
        self._covered = jnp.asarray(covered)
        self.fine_solver = FastDiagSolver(
            box.fine_grid(grid),
            DomainBC(axes=(dirichlet_axis(),) * dim), ("cc",) * dim)
        # dense-transform twin of the coarse FFT inverse, used only by
        # the sharded preconditioner path; built by
        # build_dense_coarse_solver (from OUTSIDE any trace — the
        # eigenbasis constants must not be created mid-trace), not
        # eagerly: unsharded constructions (incl. every moving-window
        # regrid rebuild) must not pay the O(n^3) host eigh for it
        self._coarse_dense_solver = None

    # -- sharding pins -------------------------------------------------------
    def _pin_c(self, x):
        """Pin a coarse-level array to the spatial sharding."""
        if self.level_sharding is None:
            return x
        return jax.lax.with_sharding_constraint(x, self.level_sharding)

    def _pin_f(self, x):
        """Pin a fine-box array replicated (the window is the SMALL
        level by design; see make_sharded_two_level_ib_step)."""
        if self.window_sharding is None:
            return x
        return jax.lax.with_sharding_constraint(x, self.window_sharding)

    def build_dense_coarse_solver(self) -> None:
        """Build the dense-periodic coarse inverse for the sharded
        preconditioner path. Call from host code (a jitted trace must
        not create the eigenbasis constants)."""
        if self._coarse_dense_solver is None:
            self._coarse_dense_solver = FastDiagSolver(
                self.grid, DomainBC.periodic(self.grid.dim),
                ("cc",) * self.grid.dim, dense_periodic=True)

    # -- composite operator --------------------------------------------------
    def _phi_eff(self, phi_c, phi_f):
        return self._pin_c(phi_c.at[self.box_sl].set(restrict_cc(phi_f)))

    def _interface_flux_correction(self, lap_c, phi_eff, phi_ext):
        return interface_flux_correction(lap_c, phi_eff, phi_ext,
                                         self.box, self.dx, self.dx_f)

    def operator(self, phi):
        """Composite Poisson operator. The covered coarse DOFs are
        decoupled identity rows at Laplacian-diagonal scale (they do not
        feed phi_eff — the slaving uses restrict(phi_f) directly), so
        the preconditioned spectrum stays Laplacian-like."""
        phi_c, phi_f = phi
        phi_eff = self._phi_eff(phi_c, phi_f)
        lap_c = stencils.laplacian(phi_eff, self.dx)
        phi_ext = self._pin_f(
            fill_fine_ghosts(phi_f, phi_eff, self.box, ghost=1))
        lap_c = self._pin_c(
            self._interface_flux_correction(lap_c, phi_eff, phi_ext))
        diag = sum(2.0 / h ** 2 for h in self.dx)
        out_c = jnp.where(self._covered, -diag * phi_c, lap_c)
        # rank-one shift removes the composite constant nullspace
        out_c = self._pin_c(out_c + diag * jnp.mean(phi_eff))
        lap_f = self._pin_f(
            _box_cc_laplacian(phi_ext, self.dx_f, self.box.fine_n))
        return (out_c, lap_f)

    def _precondition(self, r):
        if self._external_precond is not None:
            # pin the external preconditioner's output like every other
            # level crossing (the sharded path's partitioner invariant)
            p_c, p_f = self._external_precond(r)
            return (self._pin_c(p_c), self._pin_f(p_f))
        r_c, r_f = r
        diag = sum(2.0 / h ** 2 for h in self.dx)
        if self.level_sharding is not None:
            # sharded solve: the coarse exact inverse runs as dense
            # real-Fourier axis MATMULS (fastdiag dense_periodic) — the
            # SPMD partitioner distributes them like the wall-bounded
            # transforms, whereas XLA's fft thunk rejects the
            # partitioned layouts this solve produces (CPU
            # "IsMonotonicWithDim0Major" RET_CHECK)
            p_c = self._coarse_dense_solver.solve(r_c, 0.0, 1.0,
                                                  zero_nullspace=True)
        else:
            p_c = fft.solve_poisson_periodic(r_c, self.dx)
        p_c = self._pin_c(jnp.where(self._covered, -r_c / diag, p_c))
        p_f = self._pin_f(self.fine_solver.solve(r_f, 0.0, 1.0))
        return (p_c, p_f)

    # -- projection ----------------------------------------------------------
    def project(self, uc: Vel, uf: Vel,
                q_c: Optional[jnp.ndarray] = None,
                q_f: Optional[jnp.ndarray] = None
                ) -> Tuple[Vel, Vel, jnp.ndarray, jnp.ndarray]:
        grid = self.grid
        box = self.box
        div_c = stencils.divergence(uc, self.dx)
        if q_c is not None:
            div_c = div_c - q_c
        div_f = self._pin_f(_box_mac_divergence(uf, self.dx_f))
        if q_f is not None:
            div_f = div_f - q_f
        rhs_c = self._pin_c(jnp.where(self._covered, 0.0, div_c))
        sol = fgmres(self.operator, (rhs_c, div_f),
                     M=self._precondition, m=self.m, tol=self.tol,
                     restarts=self.restarts)
        from ibamr_tpu.solvers.escalation import record_solve_stats
        record_solve_stats(
            self, sol, solver="fgmres",
            use_callback=self.record_stats,
            mirrors=(getattr(self._external_precond, "__self__", None),))
        phi_c, phi_f = self._pin_c(sol.x[0]), self._pin_f(sol.x[1])
        phi_eff = self._phi_eff(phi_c, phi_f)

        # coarse correction (periodic gradient everywhere; covered and
        # interface faces are then overwritten by restriction)
        gc = stencils.gradient(phi_eff, self.dx)
        uc_new = tuple(self._pin_c(c - g) for c, g in zip(uc, gc))

        # fine correction (gradients from the ghost-extended phi)
        phi_ext = self._pin_f(fill_fine_ghosts(phi_f, phi_eff, box,
                                               ghost=1))
        uf_new = tuple(self._pin_f(c) for c in
                       box_mac_gradient_correct(uf, phi_ext, self.dx_f))

        uc_new = tuple(
            self._pin_c(c) for c in scatter_box_mac_to_coarse(
                uc_new, restrict_mac(uf_new), box))
        return uc_new, uf_new, phi_eff, phi_f


def scatter_box_mac_to_coarse(uc: Vel, ur: Vel, box: FineBox) -> Vel:
    """Overwrite the covered coarse faces (incl. the interface planes)
    with the restricted fine faces — the CoarsenSchedule sync."""
    dim = len(uc)
    out = []
    for d in range(dim):
        sl = tuple(slice(box.lo[a],
                         box.hi[a] + (1 if a == d else 0))
                   for a in range(dim))
        out.append(uc[d].at[sl].set(ur[d]))
    return tuple(out)


# --------------------------------------------------------------------------
# the two-level integrator
# --------------------------------------------------------------------------

class TwoLevelINSState(NamedTuple):
    uc: Vel
    uf: Vel
    t: jnp.ndarray
    k: jnp.ndarray


class TwoLevelINS:
    """Composite two-level INS: explicit convection + diffusion, exact
    composite projection per step (see module docstring). The explicit
    treatment bounds dt by the FINE viscous/advective limits — the
    trade for a fully matrix-free composite step; the uniform-grid
    integrator keeps CN diffusion for production runs."""

    def __init__(self, grid: StaggeredGrid, box: FineBox,
                 rho: float = 1.0, mu: float = 0.01,
                 convective: bool = True, proj_tol: float = 1e-9,
                 proj_m: int = 24, proj_restarts: int = 8,
                 precond_factory=None):
        box.validate(grid, clearance=2)
        self.grid = grid
        self.box = box
        self.fine = box.fine_grid(grid)
        self.rho = float(rho)
        self.mu = float(mu)
        self.convective = bool(convective)
        self.dx_f = tuple(h / box.ratio for h in grid.dx)
        # ``precond_factory(grid, box) -> M`` builds the (box-shaped)
        # external preconditioner — a factory, not an instance, so a
        # moving-window regrid can rebuild it at the new box instead of
        # silently dropping it (ADVICE round 2)
        self.precond_factory = precond_factory
        precond = (precond_factory(grid, box)
                   if precond_factory is not None else None)
        self.proj = CompositeProjection(grid, box, tol=proj_tol,
                                        m=proj_m, restarts=proj_restarts,
                                        preconditioner=precond)

    def initialize(self, uc: Vel) -> TwoLevelINSState:
        """Fine level seeded by the divergence-preserving prolongation
        (T10), so an initially div-free coarse field yields a div-free
        composite state."""
        uf = prolong_mac_div_preserving(uc, self.grid, self.box)
        uc_sync = scatter_box_mac_to_coarse(uc, restrict_mac(uf), self.box)
        return TwoLevelINSState(
            uc=uc_sync, uf=uf,
            t=jnp.zeros((), dtype=uc[0].dtype),
            k=jnp.zeros((), dtype=jnp.int32))

    def step(self, state: TwoLevelINSState, dt: float,
             f_c: Optional[Vel] = None,
             f_f: Optional[Vel] = None) -> TwoLevelINSState:
        """One composite step. ``f_c``/``f_f`` are per-level MAC body
        forces (f_f in box layout — e.g. the spread IB force)."""
        g = self.grid
        uc, uf = state.uc, state.uf
        rho, mu = self.rho, self.mu
        pin_c, pin_f = self.proj._pin_c, self.proj._pin_f

        # -- explicit predictor on each level ---------------------------
        lap_c = stencils.laplacian_vel(uc, g.dx)
        n_c = (convective_rate(uc, g.dx, "centered") if self.convective
               else tuple(jnp.zeros_like(c) for c in uc))
        uc_star = []
        for d in range(g.dim):
            rhs = -n_c[d] + (mu * lap_c[d]) / rho
            if f_c is not None:
                rhs = rhs + f_c[d] / rho
            uc_star.append(pin_c(uc[d] + dt * rhs))

        gext = 2
        uext = tuple(pin_f(u) for u in
                     fill_fine_ghosts_mac(uf, uc, self.box, ghost=gext))
        lap_f = _box_laplacian(uext, self.dx_f, gext, self.box.fine_n)
        if self.convective:
            n_f = _box_convective_rate(uext, self.dx_f, gext,
                                       self.box.fine_n)
        else:
            n_f = tuple(jnp.zeros_like(c) for c in lap_f)
        uf_star = []
        for d in range(g.dim):
            rhs = -n_f[d] + (mu * lap_f[d]) / rho
            if f_f is not None:
                rhs = rhs + f_f[d] / rho
            uf_star.append(pin_f(uf[d] + dt * rhs))

        # -- slave covered coarse to the fine predictor -----------------
        uc_star = tuple(pin_c(c) for c in scatter_box_mac_to_coarse(
            tuple(uc_star), restrict_mac(tuple(uf_star)), self.box))

        # -- composite projection --------------------------------------
        uc_new, uf_new, _, _ = self.proj.project(uc_star, tuple(uf_star))
        return TwoLevelINSState(uc=uc_new, uf=uf_new,
                                t=state.t + dt, k=state.k + 1)

    # -- diagnostics ---------------------------------------------------------
    def stable_dt(self, state: TwoLevelINSState, cfl: float = 0.5):
        """Advisory dt bound for the EXPLICIT predictor (host-side
        diagnostic, the reference's getMaximumTimeStepSize analog):
        min over levels of the advective CFL and the explicit viscous
        limit rho dx^2 / (2 dim mu) at that level's spacing — the fine
        level binds. Exceeding the viscous bound is the classic
        silent-NaN failure of composite explicit stepping."""
        out = jnp.asarray(jnp.inf, dtype=state.uc[0].dtype)
        for us, dx in ((state.uc, self.grid.dx), (state.uf, self.dx_f)):
            out = jnp.minimum(out, level_dt_limit(
                us, dx, self.grid.dim, self.rho, self.mu, cfl))
        return out

    def max_divergence(self, state: TwoLevelINSState):
        """(uncovered coarse incl. interface ring, fine interior)."""
        div_c = stencils.divergence(state.uc, self.grid.dx)
        div_c = jnp.where(self.proj._covered, 0.0, div_c)
        div_f = _box_mac_divergence(state.uf, self.dx_f)
        return jnp.maximum(jnp.max(jnp.abs(div_c)),
                           jnp.max(jnp.abs(div_f)))


def advance_two_level(integ: TwoLevelINS, state: TwoLevelINSState,
                      dt: float, num_steps: int,
                      f_c: Optional[Vel] = None,
                      f_f: Optional[Vel] = None) -> TwoLevelINSState:
    def body(s, _):
        return integ.step(s, dt, f_c=f_c, f_f=f_f), None

    out, _ = jax.lax.scan(body, state, None, length=num_steps)
    return out


# --------------------------------------------------------------------------
# IB on the composite hierarchy (refine around the structure)
# --------------------------------------------------------------------------

class TwoLevelIBState(NamedTuple):
    fluid: TwoLevelINSState
    X: jnp.ndarray
    U: jnp.ndarray
    mask: jnp.ndarray


def _box_mac_from_periodic(f_per: Vel) -> Vel:
    """Periodic fine-grid MAC layout (shape nf) -> box layout (+1 normal
    extent). Valid when no marker stencil wraps (structure keeps
    delta-support clearance from the box boundary), so the duplicated
    face carries zero. Delegates to the shared layout bridge."""
    return stencils.mac_complete_from_periodic(f_per)


def _periodic_from_box_mac(u_box: Vel, fine_n) -> Vel:
    return stencils.mac_periodic_from_complete(u_box, fine_n)


class TwoLevelIBINS:
    """Explicit IB coupling on the two-level composite grid: the
    structure lives inside the fine box (the canonical IBAMR usage —
    refinement tracks the immersed boundary, SURVEY.md §0), transfers
    run at FINE resolution, and the coarse level sees the restricted
    force. The structure must keep delta-support clearance from the box
    boundary (the proper-nesting analog).

    ``ib`` is any strategy exposing the marker-cloud IBStrategy seam —
    ``compute_force(X, U, t)`` plus
    ``interpolate_velocity``/``spread_force`` with the ``ctx`` protocol
    (round 4): the classic marker
    :class:`~ibamr_tpu.integrators.ib.IBMethod`, the finite-element
    :class:`~ibamr_tpu.integrators.ibfe.IBFEMethod` (the reference's
    IBFE-on-AMR configuration), incl. the prescribed-motion and
    surface-method wrappers. (The IMP material-point method carries
    deformation-gradient state through its OWN integrator and does not
    fit this seam.) Transfers go through the strategy against the FINE
    grid, so quadrature-cloud couplings and transfer engines ride the
    hierarchy unchanged. A ``fast`` transfer engine attached to the
    strategy must be built for ``box.fine_grid(grid)`` — the shared
    engine/grid guard (``ib.check_fast_grid``) rejects a mismatch."""

    def __init__(self, grid: StaggeredGrid, box: FineBox, ib,
                 rho: float = 1.0, mu: float = 0.01,
                 convective: bool = True, proj_tol: float = 1e-9,
                 proj_m: int = 24, proj_restarts: int = 8,
                 precond_factory=None):
        self.core = TwoLevelINS(grid, box, rho=rho, mu=mu,
                                convective=convective, proj_tol=proj_tol,
                                proj_m=proj_m, proj_restarts=proj_restarts,
                                precond_factory=precond_factory)
        self.grid = grid
        self.box = box
        self.fine_grid = box.fine_grid(grid)
        self.ib = ib

    def initialize(self, X0, uc: Optional[Vel] = None) -> TwoLevelIBState:
        g = self.grid
        if uc is None:
            uc = tuple(jnp.zeros(g.n, dtype=jnp.result_type(X0))
                       for _ in range(g.dim))
        fluid = self.core.initialize(uc)
        X = jnp.asarray(X0)
        return TwoLevelIBState(
            fluid=fluid, X=X, U=jnp.zeros_like(X),
            mask=jnp.ones(X.shape[0], dtype=X.dtype))

    def _interp(self, uf_box: Vel, X, mask, ctx=None):
        u_per = _periodic_from_box_mac(uf_box, self.box.fine_n)
        return self.ib.interpolate_velocity(u_per, self.fine_grid, X,
                                            mask, ctx=ctx)

    def _spread_two_level(self, F, X, mask, ctx=None):
        """Spread a Lagrangian force at configuration ``X`` onto BOTH
        hierarchy levels: fine-window MAC force + conservatively
        restricted coarse force, each routed through the composite
        projection's sharding pins. THE single definition of the
        pin/restrict/scatter sequence — the implicit integrator's
        Newton residual reuses it, so the partitioner-safe pinning
        cannot drift between the explicit and implicit paths."""
        f_per = self.ib.spread_force(F, self.fine_grid, X, mask,
                                     ctx=ctx)
        pin_c = self.core.proj._pin_c
        pin_f = self.core.proj._pin_f
        f_f = tuple(pin_f(c) for c in _box_mac_from_periodic(f_per))
        # coarse sees the conservatively restricted force in the box
        f_c = tuple(pin_c(c) for c in scatter_box_mac_to_coarse(
            tuple(jnp.zeros(self.grid.n, dtype=f_per[0].dtype)
                  for _ in range(self.grid.dim)),
            restrict_mac(f_f), self.box))
        return f_c, f_f

    def step(self, state: TwoLevelIBState, dt: float) -> TwoLevelIBState:
        fluid = state.fluid
        X_n = state.X
        U_n = self._interp(fluid.uf, X_n, state.mask)
        X_half = X_n + 0.5 * dt * U_n
        t_half = fluid.t + 0.5 * dt
        F = self.ib.compute_force(X_half, U_n, t_half)
        # one transfer context per structural position, shared by the
        # spread and the midpoint interp (the strategy seam's protocol)
        ctx = self.ib.prepare(X_half, state.mask) \
            if hasattr(self.ib, "prepare") else None
        f_c, f_f = self._spread_two_level(F, X_half, state.mask,
                                          ctx=ctx)
        fluid_new = self.core.step(fluid, dt, f_c=f_c, f_f=f_f)
        u_mid = tuple(0.5 * (a + b)
                      for a, b in zip(fluid.uf, fluid_new.uf))
        U_half = self._interp(u_mid, X_half, state.mask, ctx=ctx)
        X_new = X_n + dt * U_half
        return TwoLevelIBState(fluid=fluid_new, X=X_new, U=U_half,
                               mask=state.mask)


def advance_two_level_ib(integ: TwoLevelIBINS, state: TwoLevelIBState,
                         dt: float, num_steps: int) -> TwoLevelIBState:
    def body(s, _):
        return integ.step(s, dt), None

    out, _ = jax.lax.scan(body, state, None, length=num_steps)
    return out


def _window_lo_from_markers(grid: StaggeredGrid, X, shape,
                            clearance: int = 2) -> Tuple[int, ...]:
    """Origin of a FIXED-SHAPE window centered on the marker bbox,
    clipped to proper nesting (host-side)."""
    Xn = np.asarray(X)
    lo = []
    for d in range(grid.dim):
        c = (Xn[:, d] - grid.x_lo[d]) / grid.dx[d]
        center = 0.5 * (c.min() + c.max())
        l = int(round(center - shape[d] / 2.0))
        l = max(clearance, min(l, grid.n[d] - shape[d] - clearance))
        # the clipped window must still CONTAIN the structure (plus a
        # delta-support margin): markers outside the fine box would be
        # transferred against the wrong level silently. The framework
        # does not wrap marker coordinates, so a structure crossing the
        # periodic boundary needs a re-centered coordinate frame (or a
        # bigger window) — fail loudly instead.
        margin = 3
        if c.min() < l + margin or c.max() > l + shape[d] - margin:
            raise ValueError(
                f"axis {d}: marker span [{c.min():.1f}, {c.max():.1f}] "
                f"cells does not fit the clipped window "
                f"[{l}, {l + shape[d]}] with margin {margin}; enlarge "
                f"the window shape or re-center the domain")
        lo.append(l)
    return tuple(lo)


def regrid_two_level_ib(integ: TwoLevelIBINS, state: TwoLevelIBState,
                        move_threshold: int = 2
                        ) -> Tuple[TwoLevelIBINS, TwoLevelIBState]:
    """Host-side moving-window regrid for the composite IB/INS
    hierarchy (the marker-tagged regrid of SURVEY.md §3.4 applied to
    the FLAGSHIP path — closing round 1's 'regrid is marker-blind'
    gap): retag a fixed-shape fine window from the CURRENT markers;
    when it moves, rebuild the window integrator and transfer the fluid
    state:

    1. new fine velocity = divergence-preserving MAC prolongation of
       the coarse field over the new window (T10);
    2. surviving fine data copied across the old∩new overlap (the
       refine-schedule copy — fine-resolution information is never
       thrown away where the windows agree);
    3. one composite projection cleans the copy/prolongation seam back
       to div-free at solver tolerance.

    Runs on host between jitted chunks (the reference's regrid cadence
    is host-side too); a moved window implies one recompilation of the
    step at the new static origin — the cost model matches the
    reference's repartition-at-regrid. Returns (integ, state), both
    unchanged when the window did not move."""
    grid = integ.grid
    old = integ.box
    lo_new = _window_lo_from_markers(grid, state.X, old.shape)
    if max(abs(a - b) for a, b in zip(lo_new, old.lo)) < move_threshold:
        return integ, state

    new_box = FineBox(lo=lo_new, shape=old.shape, ratio=old.ratio)
    core = integ.core
    # carry the FULL projection configuration across the rebuild — the
    # external preconditioner is rebuilt at the new box by its factory
    # (a FAC-preconditioned run must not silently revert to the default
    # FFT+fastdiag combination mid-run, ADVICE round 2)
    integ2 = TwoLevelIBINS(grid, new_box, integ.ib, rho=core.rho,
                           mu=core.mu, convective=core.convective,
                           proj_tol=core.proj.tol, proj_m=core.proj.m,
                           proj_restarts=core.proj.restarts,
                           precond_factory=core.precond_factory)

    uc = state.fluid.uc
    # 1. prolong the coarse field over the new window
    uf_new = list(prolong_mac_div_preserving(uc, grid, new_box))
    # 2. copy surviving fine data across the overlap (fine indices)
    r = old.ratio
    ov_lo = [max(a, b) for a, b in zip(old.lo, lo_new)]
    ov_hi = [min(a, b) for a, b in zip(old.hi, new_box.hi)]
    if all(h > l for l, h in zip(ov_lo, ov_hi)):
        for d in range(grid.dim):
            src = [slice(r * (ov_lo[e] - old.lo[e]),
                         r * (ov_hi[e] - old.lo[e])
                         + (1 if e == d else 0))
                   for e in range(grid.dim)]
            dst = [slice(r * (ov_lo[e] - lo_new[e]),
                         r * (ov_hi[e] - lo_new[e])
                         + (1 if e == d else 0))
                   for e in range(grid.dim)]
            uf_new[d] = uf_new[d].at[tuple(dst)].set(
                state.fluid.uf[d][tuple(src)])
    # 3. sync + composite projection cleans the seam
    uc_sync = scatter_box_mac_to_coarse(uc, restrict_mac(tuple(uf_new)),
                                        new_box)
    uc_p, uf_p, _, _ = integ2.core.proj.project(uc_sync, tuple(uf_new))
    fluid = TwoLevelINSState(uc=uc_p, uf=uf_p, t=state.fluid.t,
                             k=state.fluid.k)
    return integ2, TwoLevelIBState(fluid=fluid, X=state.X, U=state.U,
                                   mask=state.mask)


def level_dt_limit(us, dx, dim: int, rho: float, mu: float,
                   cfl: float = 0.5):
    """One level's explicit-predictor dt bound: advective CFL against
    the level's max speed, and the explicit viscous limit
    rho dx^2/(2 dim mu). Shared by the two-level and L-level advisory
    diagnostics so the convention cannot diverge."""
    dt0 = us[0].dtype
    umax = jnp.maximum(jnp.asarray(1e-12, dtype=dt0),
                       jnp.max(jnp.stack([jnp.max(jnp.abs(c))
                                          for c in us])))
    out = cfl * min(dx) / umax
    if mu > 0.0:
        out = jnp.minimum(out, rho * min(dx) ** 2 / (2.0 * dim * mu))
    return out


def advance_with_regrids(integ, state, dt: float, num_steps: int,
                         regrid_interval: int, advance_fn, regrid_fn,
                         on_chunk=None):
    """Shared regrid-cadence driver (the reference's regrid loop shape,
    SURVEY.md §3.4): jitted chunks of ``regrid_interval`` steps with
    host-side ``regrid_fn(integ, state)`` between them.

    The jitted chunk is cached per (integrator, length): a static
    window re-traces nothing; only a MOVED window (new integrator, new
    static origins) compiles anew — the documented cost model. Used by
    both the two-level and the L-level moving-window paths.

    ``on_chunk(integ, state, steps_done)``: optional host-side hook
    after every chunk (metrics/viz/restart) — drivers should use it
    rather than calling this function repeatedly, which would discard
    the chunk cache (and recompile) at every call."""
    chunks = {}

    def chunk(n):
        key = (id(integ), n)
        if key not in chunks:
            local_integ = integ

            def run(s, dt):
                return advance_fn(local_integ, s, dt, n)

            chunks[key] = jax.jit(run)
        return chunks[key]

    done = 0
    while done < num_steps:
        n = min(regrid_interval, num_steps - done)
        state = chunk(n)(state, dt)
        done += n
        if on_chunk is not None:
            on_chunk(integ, state, done)
        if done < num_steps:
            integ2, state = regrid_fn(integ, state)
            if integ2 is not integ:
                # the moved window's old executables are unreachable
                # (cache keys are id-based); drop them so a long run
                # with many moves does not pin stale compilations
                chunks.clear()
                integ = integ2
    return integ, state


def advance_two_level_ib_regridding(integ: TwoLevelIBINS,
                                    state: TwoLevelIBState, dt: float,
                                    num_steps: int,
                                    regrid_interval: int = 20,
                                    on_chunk=None
                                    ) -> Tuple[TwoLevelIBINS,
                                               TwoLevelIBState]:
    """Advance with the window tracking the structure: jitted chunks of
    ``regrid_interval`` steps with host-side marker-tagged regrids in
    between (the reference's regrid cadence)."""
    return advance_with_regrids(integ, state, dt, num_steps,
                                regrid_interval, advance_two_level_ib,
                                regrid_two_level_ib, on_chunk=on_chunk)


def box_from_markers(grid: StaggeredGrid, X, pad: int = 4,
                     even: bool = True) -> FineBox:
    """Tag the fine box from marker positions (host-side, at setup /
    regrid time): the smallest coarse-cell box covering the structure
    plus ``pad`` cells of clearance (delta support + motion headroom) —
    the marker-tagging half of StandardTagAndInitialize (SURVEY.md
    §3.4). ``even`` rounds the box to even extents (clean restriction)."""
    Xn = np.asarray(X)
    lo, hi = [], []
    for d in range(grid.dim):
        c = (Xn[:, d] - grid.x_lo[d]) / grid.dx[d]
        l = int(np.floor(c.min())) - pad
        h = int(np.ceil(c.max())) + pad
        l = max(l, 2)
        h = min(h, grid.n[d] - 2)
        if even and (h - l) % 2:
            h = h - 1 if h - l > 2 else h
            if (h - l) % 2:
                l = l + 1
        lo.append(l)
        hi.append(h)
    return FineBox(lo=tuple(lo), shape=tuple(h - l for l, h in
                                             zip(lo, hi)))
