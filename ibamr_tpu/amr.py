"""Two-level static mesh refinement: restriction, prolongation, coarse-fine
interpolation, and a subcycled composite advance with refluxing.

Reference parity: the coarse-fine machinery of T10 (SURVEY.md §2.1 —
``CartCellDoubleQuadraticCFInterpolation``, ``CartSideDoubleDivPreservingRefine``,
``CartCellDoubleCubicCoarsen``) and the level-by-level AMR parallel
structure S4, restricted to the two-level static case of the build plan
(SURVEY.md §7.2 stage 8; dynamic regridding is stage 11, on top of this).

TPU-first redesign (SURVEY.md §7.1): the fine level is ONE dense array
over a static index box (``FineBox``) — no patch lists, no schedules. All
transfer operators are reshapes/gathers with static shapes:

- restriction        = block-mean reshape (cell) / coincident-face mean (MAC);
- CF ghost fill      = separable quadratic (3-point Lagrange) gather from
                       the periodic coarse level at fine ghost centers;
- div-preserving MAC prolongation = transverse/normal linear interpolation
  (flux-preserving 3/4–1/4 weights) followed by an EXACT per-coarse-cell
  Neumann correction: the 2^dim-subcell Poisson pseudo-inverse is a single
  precomputed (2^dim x 2^dim) matrix applied to all cells with one matmul
  — the reference's recursive Fortran reconstruction becomes an MXU op.

The composite advance is the classic subcycled flux-form scheme: one
coarse step, ``ratio`` fine substeps with space-time interpolated ghost
data, restriction of the fine solution onto covered coarse cells, and a
reflux correction that replaces the coarse flux through the coarse-fine
interface with the time/space-averaged fine flux — total mass is then
conserved to roundoff, which the tests enforce.
"""

from __future__ import annotations

import dataclasses
import functools
import itertools
from typing import Optional, Sequence, Tuple

import numpy as np
import jax.numpy as jnp

from ibamr_tpu.grid import StaggeredGrid

Vel = Tuple[jnp.ndarray, ...]


# --------------------------------------------------------------------------
# Geometry
# --------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class FineBox:
    """A static refined region: coarse cells [lo, lo+shape) at ``ratio``x.

    The box must sit strictly inside the periodic coarse domain (>=2 cells
    of clearance) so coarse-fine stencils never wrap around the domain —
    the same restriction the reference enforces via proper nesting.
    """

    lo: Tuple[int, ...]        # coarse cell index of the box lower corner
    shape: Tuple[int, ...]     # box extent in coarse cells
    ratio: int = 2

    def __post_init__(self):
        object.__setattr__(self, "lo", tuple(int(v) for v in self.lo))
        object.__setattr__(self, "shape", tuple(int(v) for v in self.shape))
        assert self.ratio == 2, "only refinement ratio 2 is implemented"
        assert all(s >= 1 for s in self.shape)

    @property
    def dim(self) -> int:
        return len(self.lo)

    @property
    def hi(self) -> Tuple[int, ...]:
        return tuple(l + s for l, s in zip(self.lo, self.shape))

    @property
    def fine_n(self) -> Tuple[int, ...]:
        return tuple(s * self.ratio for s in self.shape)

    def validate(self, grid: StaggeredGrid, clearance: int = 2) -> None:
        assert self.dim == grid.dim
        for d in range(grid.dim):
            assert clearance <= self.lo[d], \
                f"fine box too close to domain edge on axis {d}"
            assert self.hi[d] <= grid.n[d] - clearance, \
                f"fine box too close to domain edge on axis {d}"

    def fine_grid(self, grid: StaggeredGrid) -> StaggeredGrid:
        """Geometry of the refined region as its own (non-periodic) grid."""
        dx = grid.dx
        x_lo = tuple(grid.x_lo[d] + self.lo[d] * dx[d]
                     for d in range(grid.dim))
        x_up = tuple(grid.x_lo[d] + self.hi[d] * dx[d]
                     for d in range(grid.dim))
        return StaggeredGrid(n=self.fine_n, x_lo=x_lo, x_up=x_up)


# --------------------------------------------------------------------------
# Restriction (fine -> coarse)
# --------------------------------------------------------------------------

def restrict_cc(fine: jnp.ndarray, ratio: int = 2) -> jnp.ndarray:
    """Conservative block-mean coarsening of cell data (the constant-
    preserving member of the reference's coarsen-op family T10)."""
    dim = fine.ndim
    shape = []
    for d in range(dim):
        assert fine.shape[d] % ratio == 0
        shape += [fine.shape[d] // ratio, ratio]
    arr = fine.reshape(shape)
    for d in reversed(range(dim)):
        arr = arr.mean(axis=2 * d + 1)
    return arr


def box_mac_to_cc(uf):
    """Each box MAC component (complete faces: shape n + e_d) to cell
    centers — the box-layout twin of :func:`ibamr_tpu.ops.stencils.fc_to_cc`
    (dimension-generic; viz/diagnostic use)."""
    dim = len(uf)
    out = []
    for d, c in enumerate(uf):
        lo = tuple(slice(0, -1) if e == d else slice(None)
                   for e in range(dim))
        hi = tuple(slice(1, None) if e == d else slice(None)
                   for e in range(dim))
        out.append(0.5 * (c[lo] + c[hi]))
    return tuple(out)


def restrict_mac(u_fine: Sequence[jnp.ndarray], ratio: int = 2) -> Vel:
    """Coarsen box MAC data (component d has shape fine_n + e_d): coarse
    face value = mean of the 2^(dim-1) coincident fine faces (even normal
    index). Preserves fluxes through coarse faces exactly."""
    out = []
    for d, uf in enumerate(u_fine):
        dim = uf.ndim
        # keep only fine faces lying on coarse face planes
        sl = [slice(None)] * dim
        sl[d] = slice(0, None, ratio)
        arr = uf[tuple(sl)]
        # mean over transverse fine offsets
        shape = []
        for a in range(dim):
            if a == d:
                shape.append(arr.shape[a])
            else:
                shape += [arr.shape[a] // ratio, ratio]
        arr = arr.reshape(shape)
        # mean trailing ratio axes (those after each transverse axis)
        k = 0
        axes = []
        for a in range(dim):
            if a == d:
                k += 1
            else:
                axes.append(k + 1)
                k += 2
        arr = arr.mean(axis=tuple(axes))
        out.append(arr)
    return tuple(out)


# --------------------------------------------------------------------------
# Separable Lagrange interpolation from the periodic coarse level
# --------------------------------------------------------------------------

def interp_periodic(field: jnp.ndarray, pts: jnp.ndarray,
                    order: int = 2) -> jnp.ndarray:
    """Interpolate a periodic grid array at continuous index coordinates.

    ``pts`` is (..., dim) in units where grid point ``i`` sits at index
    coordinate ``i`` (callers fold in the 0.5 cell-center offset).
    ``order``=1 (2-point linear) or 2 (3-point quadratic — the CF
    interpolation order of the reference's T10 ops).
    """
    dim = field.ndim
    flat_pts = pts.reshape(-1, dim)
    npts = flat_pts.shape[0]

    if order == 2:
        offs = jnp.arange(-1, 2)

        def weights(t):
            # t in [-0.5, 0.5]: Lagrange through nodes {-1, 0, +1}
            return jnp.stack([0.5 * t * (t - 1.0),
                              (1.0 - t) * (1.0 + t),
                              0.5 * t * (t + 1.0)], axis=-1)

        def base(x):
            return jnp.round(x).astype(jnp.int32)
    elif order == 1:
        offs = jnp.arange(0, 2)

        def weights(t):
            return jnp.stack([1.0 - t, t], axis=-1)

        def base(x):
            return jnp.floor(x).astype(jnp.int32)
    else:
        raise ValueError(f"unsupported order {order}")

    lin = None
    wgt = None
    for d in range(dim):
        x = flat_pts[:, d]
        b = base(x)
        t = x - b.astype(x.dtype)
        idx = jnp.mod(b[:, None] + offs[None, :], field.shape[d])
        w = weights(t)
        if lin is None:
            lin, wgt = idx, w
        else:
            s = offs.shape[0]
            lin = lin[..., :, None] * field.shape[d] + idx.reshape(
                (npts,) + (1,) * (lin.ndim - 1) + (s,))
            wgt = wgt[..., :, None] * w.reshape(
                (npts,) + (1,) * (wgt.ndim - 1) + (s,))
    vals = jnp.take(field.reshape(-1), lin.reshape(npts, -1), axis=0)
    out = jnp.sum(vals * wgt.reshape(npts, -1), axis=-1)
    return out.reshape(pts.shape[:-1])


def _fine_to_coarse_coord(box: FineBox, axis: int,
                          i: jnp.ndarray) -> jnp.ndarray:
    """Coarse *cell-center index* coordinate of fine cell ``i`` (may be a
    ghost index < 0 or >= fine_n). Physical position in coarse cell units
    is lo + (i + 0.5)/r; coarse center j sits at j + 0.5, so the index
    coordinate is that minus 0.5. The single registration-formula source
    for every CF interpolation below."""
    return box.lo[axis] + (i + 0.5) / box.ratio - 0.5


def _fine_cell_index_coords(box: FineBox, ghost: int,
                            dtype=jnp.float64) -> jnp.ndarray:
    """Continuous coarse cell-center index coordinates of fine cell
    centers (including ``ghost`` fine ghost layers), shape (*nf+2g, dim)."""
    axes = [_fine_to_coarse_coord(
        box, d, jnp.arange(-ghost, box.fine_n[d] + ghost, dtype=dtype))
        for d in range(box.dim)]
    grids = jnp.meshgrid(*axes, indexing="ij")
    return jnp.stack(grids, axis=-1)


def prolong_cc(coarse: jnp.ndarray, box: FineBox, ghost: int = 0,
               order: int = 2) -> jnp.ndarray:
    """Interpolate coarse cell data onto fine cell centers of ``box``
    (plus ``ghost`` fine ghost layers) — initial fill / CF ghost fill."""
    pts = _fine_cell_index_coords(box, ghost, dtype=coarse.dtype)
    return interp_periodic(coarse, pts, order=order)


@functools.lru_cache(maxsize=32)
def _ghost_slab_geometry(box: FineBox, ghost: int, dtype_name: str):
    """Static ghost-shell geometry: per slab, the padded-array slice and
    the coarse index coordinates of its points. One slab pair per axis in
    onion order (slabs of earlier axes carry the corners); cached because
    it depends only on (box, ghost). Built with NUMPY so the cached
    values stay concrete — jnp ops executed while tracing a lax loop
    would cache leaked tracers."""
    dim = box.dim
    g = ghost
    nf = box.fine_n
    dtype = np.dtype(dtype_name)
    slabs = []
    for d in range(dim):
        for side in (0, 1):
            rng = []
            for a in range(dim):
                if a < d:                       # corners owned by axis < d
                    rng.append((g, g + nf[a]))
                elif a == d:
                    rng.append((0, g) if side == 0
                               else (nf[a] + g, nf[a] + 2 * g))
                else:
                    rng.append((0, nf[a] + 2 * g))
            axes = [np.asarray(_fine_to_coarse_coord(
                box, a, np.arange(lo_i - g, hi_i - g, dtype=dtype)))
                for a, (lo_i, hi_i) in enumerate(rng)]
            pts = np.stack(np.meshgrid(*axes, indexing="ij"), axis=-1)
            sl = tuple(slice(lo_i, hi_i) for lo_i, hi_i in rng)
            slabs.append((sl, pts))
    return tuple(slabs)


def fill_fine_ghosts(fine: jnp.ndarray, coarse: jnp.ndarray, box: FineBox,
                     ghost: int) -> jnp.ndarray:
    """Pad the fine interior with ghost layers interpolated from coarse
    (quadratic — T10's CF interpolation), keeping interior values exact.
    Only the O(surface) ghost shell is interpolated, from precomputed
    static slab geometry.

    Assembly is CONCATENATION in reverse-axis onion order (each axis's
    slab pair spans the interior of earlier axes and the full extent of
    later ones), not scatter-into-zeros: the SPMD partitioner
    miscompiles the repeated static-slab ``.at[sl].set`` chain when the
    result is pinned to a spatial sharding (wrong values, observed on
    the 8-device CPU mesh in the sharded-window S4 path), while
    gather + concatenate partitions correctly. Values are identical."""
    out = fine
    slabs = _ghost_slab_geometry(box, ghost, coarse.dtype.name)
    for d in reversed(range(box.dim)):
        _, lo_pts = slabs[2 * d]
        _, hi_pts = slabs[2 * d + 1]
        lo = interp_periodic(coarse, lo_pts, order=2)
        hi = interp_periodic(coarse, hi_pts, order=2)
        out = jnp.concatenate([lo, out, hi], axis=d)
    return out


# --------------------------------------------------------------------------
# Divergence-preserving MAC prolongation
# --------------------------------------------------------------------------

def _neumann_block_pinv(dim: int, dx_f: Sequence[float]) -> np.ndarray:
    """Pseudo-inverse of the 2^dim-subcell Neumann Laplacian of one coarse
    cell (zero flux through the coarse cell boundary). Host-precomputed."""
    n = 2 ** dim
    A = np.zeros((n, n))
    cells = list(itertools.product(*[range(2)] * dim))
    index = {c: i for i, c in enumerate(cells)}
    for c in cells:
        i = index[c]
        for d in range(dim):
            for s in (-1, 1):
                nb = list(c)
                nb[d] += s
                if 0 <= nb[d] < 2:
                    j = index[tuple(nb)]
                    w = 1.0 / (dx_f[d] ** 2)
                    A[i, i] -= w
                    A[i, j] += w
    return np.linalg.pinv(A)


def _box_mac_divergence(u: Sequence[jnp.ndarray],
                        dx: Sequence[float]) -> jnp.ndarray:
    """Divergence on a box MAC layout (component d has +1 extent on d)."""
    dim = len(u)
    out = None
    for d in range(dim):
        up = [slice(None)] * dim
        lo = [slice(None)] * dim
        up[d] = slice(1, None)
        lo[d] = slice(0, -1)
        term = (u[d][tuple(up)] - u[d][tuple(lo)]) / dx[d]
        out = term if out is None else out + term
    return out


def prolong_mac_div_preserving(u_coarse: Sequence[jnp.ndarray],
                               grid: StaggeredGrid,
                               box: FineBox) -> Vel:
    """Prolong a periodic coarse MAC field onto ``box`` so that each fine
    cell's divergence EQUALS its parent coarse cell's divergence (so
    discretely div-free stays div-free) — the
    ``CartSideDoubleDivPreservingRefine`` contract (T10).

    Returns box MAC arrays (component d has shape fine_n + e_d).
    Scheme: flux-preserving linear interpolation, then an exact local
    Neumann Poisson correction per coarse cell (one matmul, see module
    docstring).
    """
    dim = grid.dim
    r = box.ratio
    dx = grid.dx
    dx_f = tuple(h / r for h in dx)
    nb = box.shape
    dtype = u_coarse[0].dtype

    # --- step A: componentwise interpolation ---------------------------
    u_fine = []
    for d in range(dim):
        uc = u_coarse[d]
        # transverse: 3/4-1/4 linear interpolation at fine cell centers;
        # each coarse-face pair averages back to the coarse value (flux
        # preserving). Work on the coarse array, then slice the box.
        arr = uc
        for a in range(dim):
            if a == d:
                continue
            # central-slope linear reconstruction at offsets -/+ 1/4:
            # the pair averages to the coarse value EXACTLY, so the flux
            # through every coarse face is preserved (the property the
            # Neumann correction's solvability relies on)
            slope = 0.5 * (jnp.roll(arr, -1, a) - jnp.roll(arr, 1, a))
            lo_v = arr - 0.25 * slope   # fine offset -1/4
            hi_v = arr + 0.25 * slope   # fine offset +1/4
            arr = jnp.stack([lo_v, hi_v], axis=arr.ndim)  # append fine-offset axis
        # arr axes: dim coarse axes then one 2-wide axis per transverse a
        # (in increasing a order, skipping d). Slice the box — with one
        # extra plane along d, since face index == cell index puts coarse
        # face planes lo[d]..hi[d] inclusive at slice(lo, hi+1) (the box
        # clearance guarantees hi+1 <= n without wrapping).
        box_sl = tuple(slice(box.lo[a],
                             box.hi[a] + (1 if a == d else 0))
                       for a in range(dim))
        arr = arr[box_sl]
        # interleave transverse fine axes: move each (coarse_a, fine_a)
        # pair together then reshape to fine extent
        perm = []
        trans_axes = [a for a in range(dim) if a != d]
        for a in range(dim):
            perm.append(a)
            if a != d:
                perm.append(dim + trans_axes.index(a))
        arr = arr.transpose(perm)
        new_shape = tuple(nb[a] * r if a != d else nb[a] + 1
                          for a in range(dim))
        planes = arr.reshape(new_shape)   # nb[d]+1 coarse face planes
        # insert midplanes: average of adjacent coarse planes
        lo_p = [slice(None)] * dim
        hi_p = [slice(None)] * dim
        lo_p[d] = slice(0, -1)
        hi_p[d] = slice(1, None)
        mid = 0.5 * (planes[tuple(lo_p)] + planes[tuple(hi_p)])
        # interleave: coarse-plane 0, mid 0, coarse-plane 1, mid 1, ...
        nfd = nb[d] * r
        shape_f = list(planes.shape)
        shape_f[d] = nfd + 1
        out = jnp.zeros(shape_f, dtype=dtype)
        ev = [slice(None)] * dim
        od = [slice(None)] * dim
        ev[d] = slice(0, None, 2)
        od[d] = slice(1, None, 2)
        out = out.at[tuple(ev)].set(planes)
        out = out.at[tuple(od)].set(mid)
        u_fine.append(out)

    # --- step B: exact local Neumann correction ------------------------
    from ibamr_tpu.ops import stencils
    div_c = stencils.divergence(u_coarse, dx)
    box_sl = tuple(slice(box.lo[a], box.hi[a]) for a in range(dim))
    target = div_c[box_sl]                                # (nb,)
    d0 = _box_mac_divergence(u_fine, dx_f)                # (nf,)
    # block-reshape defect to (ncells, 2^dim)
    blk = d0.reshape([v for a in range(dim) for v in (nb[a], r)])
    perm = [2 * a for a in range(dim)] + [2 * a + 1 for a in range(dim)]
    blk = blk.transpose(perm).reshape(int(np.prod(nb)), r ** dim)
    tgt = target.reshape(-1, 1)
    defect = tgt - blk                                    # (ncells, 2^dim)

    pinv = jnp.asarray(_neumann_block_pinv(dim, dx_f), dtype=dtype)
    phi = defect @ pinv.T                                 # (ncells, 2^dim)
    phi = phi.reshape([nb[a] for a in range(dim)] + [r] * dim)
    inv_perm = np.argsort(perm)
    phi = phi.transpose(inv_perm).reshape(box.fine_n)

    # add grad(phi) on block-interior faces (odd face index along d)
    out = []
    for d in range(dim):
        uf = u_fine[d]
        lo_p = [slice(None)] * dim
        hi_p = [slice(None)] * dim
        lo_p[d] = slice(0, None, 2)   # phi at subcell 0 of each block
        hi_p[d] = slice(1, None, 2)   # phi at subcell 1
        g = (phi[tuple(hi_p)] - phi[tuple(lo_p)]) / dx_f[d]
        od = [slice(None)] * dim
        od[d] = slice(1, None, 2)
        uf = uf.at[tuple(od)].add(g)
        out.append(uf)
    return tuple(out)


# --------------------------------------------------------------------------
# Two-level subcycled advection-diffusion advance with refluxing
# --------------------------------------------------------------------------

class TwoLevelAdvDiff:
    """Composite two-level advance of dQ/dt + div(uQ) = kappa lap(Q) on
    one STATIC fine box.

    Reference parity: the level-by-level subcycled advance + flux
    synchronization of the AMR integrators (SURVEY.md §3.4, S4, T10).
    Thin facade over the dynamic-origin core
    (:class:`ibamr_tpu.amr_dynamic.DynamicTwoLevelAdvDiff`) with the
    window origin pinned to ``box.lo`` — one implementation of the
    subcycled flux/reflux machinery serves both the static and the
    moving-window case.
    """

    def __init__(self, grid: StaggeredGrid, box: FineBox,
                 kappa: float = 0.0, scheme: str = "centered",
                 u_coarse: Optional[Vel] = None,
                 u_fine: Optional[Vel] = None):
        from ibamr_tpu.amr_dynamic import DynamicTwoLevelAdvDiff
        box.validate(grid)
        self.grid = grid
        self.box = box
        self.kappa = float(kappa)
        self.scheme = scheme
        self.fine = box.fine_grid(grid)
        self.dx_f = tuple(h / box.ratio for h in grid.dx)
        self._core = DynamicTwoLevelAdvDiff(
            grid, box.shape, kappa=kappa, scheme=scheme,
            u_c=u_coarse, u_f=u_fine, ratio=box.ratio, clearance=1)
        self._lo = jnp.asarray(box.lo, dtype=jnp.int32)

    # -- composite step ------------------------------------------------------
    def step(self, Qc: jnp.ndarray, Qf: jnp.ndarray,
             dt: float) -> Tuple[jnp.ndarray, jnp.ndarray]:
        from ibamr_tpu.amr_dynamic import AMRState
        out = self._core.step(AMRState(Qc=Qc, Qf=Qf, lo=self._lo), dt)
        return out.Qc, out.Qf

    # -- diagnostics ---------------------------------------------------------
    def total(self, Qc: jnp.ndarray, Qf: jnp.ndarray) -> jnp.ndarray:
        """Composite conserved integral: uncovered coarse + fine."""
        from ibamr_tpu.amr_dynamic import AMRState
        return self._core.total(AMRState(Qc=Qc, Qf=Qf, lo=self._lo))

    def initialize(self, fn, dtype=jnp.float64):
        """Evaluate ``fn(coords_tuple) -> array`` on both levels."""
        Qc = jnp.asarray(fn(self.grid.cell_centers(dtype)), dtype=dtype)
        Qf = jnp.asarray(fn(self.fine.cell_centers(dtype)), dtype=dtype)
        return jnp.broadcast_to(Qc, self.grid.n), \
            jnp.broadcast_to(Qf, self.fine.n)
