"""L-level nested AMR hierarchy for advection-diffusion (T4/S4
completion: composite math beyond two levels).

Reference parity: the general ``PatchHierarchy`` with
``max_levels > 2`` — recursive level-by-level subcycled advance with
per-pair coarse-fine synchronization (SURVEY.md §3.4: each level
advances r substeps per parent step; restriction + refluxing run at
EVERY coarse-fine interface, not just one). The two-level machinery of
:mod:`ibamr_tpu.amr` / :mod:`ibamr_tpu.amr_dynamic` is the building
block; this module composes the same primitives recursively.

TPU-first shape: the hierarchy is a static tuple of dense per-level box
arrays (one fixed box per level, nested with clearance). The recursion
over levels unrolls at trace time — an L-level composite step compiles
into ONE XLA computation with no host control flow; level l advances
2^l substeps per composite step (ratio-2 subcycling), all unrolled.

Conservation: advective+diffusive face fluxes; covered regions are
restricted from the finer level and the uncovered neighbor cells
refluxed with (time-averaged transverse-restricted fine flux - coarse
flux) at every CF interface, so the composite integral is conserved to
roundoff (tested)."""

from __future__ import annotations

from typing import Callable, List, NamedTuple, Optional, Sequence, Tuple

import numpy as np
import jax.numpy as jnp

from ibamr_tpu.amr import FineBox, fill_fine_ghosts, restrict_cc
from ibamr_tpu.grid import StaggeredGrid

Array = jnp.ndarray
Vel = Tuple[Array, ...]


class LevelSpec(NamedTuple):
    """Static geometry of one level: its box in the PARENT level's
    index space (None for the root) and its own grid geometry."""
    box: Optional[FineBox]
    grid: StaggeredGrid


def build_hierarchy(grid: StaggeredGrid,
                    boxes: Sequence[FineBox]) -> List[LevelSpec]:
    """Validate and materialize an L-level nested hierarchy: ``boxes[l]``
    is level l+1's box inside level l. Each box keeps >= 2 cells of
    clearance inside its parent so the quadratic CF interpolation
    stencils and the interface refluxing stay interior."""
    levels = [LevelSpec(box=None, grid=grid)]
    parent = grid
    for box in boxes:
        box.validate(parent)
        fine = box.fine_grid(parent)
        levels.append(LevelSpec(box=box, grid=fine))
        parent = fine
    return levels


class MultiLevelAdvDiff:
    """Composite L-level advance of dQ/dt + div(u Q) = kappa lap(Q),
    velocity frozen per level (the transport configuration of the
    reference's adv-diff + AMR acceptance tests).

    Level 0 is periodic; levels 1..L-1 are nested ratio-2 boxes.
    ``vel_fn(mesh_tuple) -> tuple(component arrays)`` is evaluated at
    every level's faces at build time."""

    GHOST = 1      # centered/upwind fluxes need one ghost layer

    def __init__(self, grid: StaggeredGrid, boxes: Sequence[FineBox],
                 kappa: float = 0.0, scheme: str = "centered",
                 vel_fn: Optional[Callable] = None,
                 dtype=jnp.float64):
        self.levels = build_hierarchy(grid, boxes)
        self.L = len(self.levels)
        self.kappa = float(kappa)
        if scheme not in ("centered", "upwind"):
            raise ValueError(f"unknown scheme {scheme!r}")
        self.scheme = scheme
        import jax

        self.dtype = jax.dtypes.canonicalize_dtype(dtype)
        # Optional sharding pinned at the two level-synchronization
        # points (set by parallel.mesh.make_sharded_multilevel_step):
        # the CF ghost-extended child array and each level's
        # post-flux-update array. These are the hierarchy's boundary-
        # exchange moments; pinning them (replicated) makes the
        # exchanges explicit all-gathers and keeps XLA's SPMD
        # partitioner from mis-propagating through the scatter/gather
        # composites (observed wrong-value miscompilation when left
        # unconstrained). Stencil/flux compute stays sharded.
        self.sync_sharding = None

        # face velocities per level: component d on faces along d.
        # level 0: periodic lower-face shape n; levels >= 1: complete
        # faces, shape n + e_d.
        self.u_faces: List[Optional[Vel]] = []
        for l, spec in enumerate(self.levels):
            if vel_fn is None:
                self.u_faces.append(None)
                continue
            g = spec.grid
            comps = []
            for d in range(g.dim):
                shape = tuple(g.n[e] + (1 if (l > 0 and e == d) else 0)
                              for e in range(g.dim))
                coords = []
                for e in range(g.dim):
                    if e == d:
                        c = g.x_lo[e] + np.arange(shape[e]) * g.dx[e]
                    else:
                        c = g.x_lo[e] + (np.arange(shape[e]) + 0.5) \
                            * g.dx[e]
                    coords.append(c)
                mesh = np.meshgrid(*coords, indexing="ij")
                comps.append(jnp.asarray(vel_fn(mesh)[d],
                                         dtype=self.dtype))
            self.u_faces.append(tuple(comps))

    # ------------------------------------------------------------------
    def _sync(self, x: Array) -> Array:
        """Apply the level-synchronization sharding pin (no-op when
        unsharded)."""
        if self.sync_sharding is None:
            return x
        import jax

        return jax.lax.with_sharding_constraint(x, self.sync_sharding)

    def initialize(self, fn) -> Tuple[Array, ...]:
        out = []
        for spec in self.levels:
            Q = jnp.asarray(fn(spec.grid.cell_centers(self.dtype)),
                            dtype=self.dtype)
            out.append(jnp.broadcast_to(Q, spec.grid.n))
        return tuple(out)

    # -- flux machinery -------------------------------------------------
    def _fluxes(self, l: int, Q: Array, Qg: Optional[Array]) -> Vel:
        """Face fluxes of u*Q - kappa*dQ/dx on level l. Level 0 uses
        periodic rolls (lower-face arrays); levels >= 1 use the 1-ghost
        extension ``Qg`` (complete-face arrays)."""
        g = self.levels[l].grid
        dim = g.dim
        out = []
        for d in range(dim):
            h = g.dx[d]
            if l == 0:
                QL, QR = jnp.roll(Q, 1, axis=d), Q
            else:
                lo = [slice(1, 1 + g.n[e]) for e in range(dim)]
                hi = [slice(1, 1 + g.n[e]) for e in range(dim)]
                lo[d] = slice(0, g.n[d] + 1)
                hi[d] = slice(1, g.n[d] + 2)
                QL, QR = Qg[tuple(lo)], Qg[tuple(hi)]
            u = self.u_faces[l][d]
            if self.scheme == "upwind":
                adv = jnp.where(u > 0, u * QL, u * QR)
            else:
                adv = u * 0.5 * (QL + QR)
            out.append(adv - self.kappa * (QR - QL) / h)
        return tuple(out)

    @staticmethod
    def _div(F: Vel, g: StaggeredGrid, complete: bool) -> Array:
        acc = None
        for d, f in enumerate(F):
            if complete:
                lo = [slice(None)] * g.dim
                hi = [slice(None)] * g.dim
                lo[d] = slice(0, -1)
                hi[d] = slice(1, None)
                t = (f[tuple(hi)] - f[tuple(lo)]) / g.dx[d]
            else:
                t = (jnp.roll(f, -1, d) - f) / g.dx[d]
            acc = t if acc is None else acc + t
        return acc

    @staticmethod
    def _bdry_slabs(F: Vel) -> List[Tuple[Array, Array]]:
        """(lo, hi) boundary-face flux slabs per axis of a complete-face
        flux tuple."""
        out = []
        for d, f in enumerate(F):
            lo_sl = [slice(None)] * f.ndim
            hi_sl = [slice(None)] * f.ndim
            lo_sl[d] = slice(0, 1)
            hi_sl[d] = slice(-1, None)
            out.append((f[tuple(lo_sl)], f[tuple(hi_sl)]))
        return out

    @staticmethod
    def _transverse_restrict(slab: Array, d: int, r: int) -> Array:
        """Mean over r-blocks in every axis except d (slab has extent 1
        along d)."""
        dim = slab.ndim
        shape = []
        for a in range(dim):
            if a == d:
                shape += [1]
            else:
                shape += [slab.shape[a] // r, r]
        arr = slab.reshape(shape)
        mean_axes = []
        i = 0
        for a in range(dim):
            if a == d:
                i += 1
            else:
                mean_axes.append(i + 1)
                i += 2
        return arr.mean(axis=tuple(mean_axes))

    # -- recursive composite step ---------------------------------------
    def _advance_level(self, l: int, Qs: List[Array],
                       p_ghost_src: Optional[Array], dt: float
                       ) -> Tuple[List[Array],
                                  Optional[List[Tuple[Array, Array]]]]:
        """Advance level l (and recursively all finer levels) by ONE
        step of its local ``dt``. ``p_ghost_src`` is the parent array
        (time-interpolated to this substep's start) for CF ghosts.
        Returns the updated arrays and level l's boundary-face flux
        slabs (None at the root) for the parent's reflux."""
        spec = self.levels[l]
        g = spec.grid

        Q_old = Qs[l]
        if l == 0:
            F = self._fluxes(0, Q_old, None)
            Q_new = self._sync(Q_old - dt * self._div(F, g,
                                                      complete=False))
        else:
            Qg = self._sync(fill_fine_ghosts(Q_old, p_ghost_src,
                                             spec.box,
                                             ghost=self.GHOST))
            F = self._fluxes(l, Q_old, Qg)
            Q_new = self._sync(Q_old - dt * self._div(F, g,
                                                      complete=True))

        Qs = list(Qs)
        Qs[l] = Q_new

        if l + 1 < self.L:
            child = self.levels[l + 1]
            box = child.box
            r = box.ratio
            dim = g.dim
            acc: Optional[List[Tuple[Array, Array]]] = None
            for m in range(r):
                theta = m / r
                p_src = (1.0 - theta) * Q_old + theta * Q_new
                Qs, slabs = self._advance_level(l + 1, Qs, p_src,
                                                dt / r)
                if acc is None:
                    acc = slabs
                else:
                    acc = [(a0 + s0, a1 + s1)
                           for (a0, a1), (s0, s1) in zip(acc, slabs)]

            # restriction onto the covered region of level l
            box_sl = tuple(slice(box.lo[a], box.hi[a])
                           for a in range(dim))
            Ql = Qs[l].at[box_sl].set(restrict_cc(Qs[l + 1]))

            # reflux level l's uncovered neighbors at the CF interface
            for d in range(dim):
                favg_lo = self._transverse_restrict(acc[d][0], d, r) / r
                favg_hi = self._transverse_restrict(acc[d][1], d, r) / r
                # coarse flux planes through the interface faces
                lo_face = [slice(box.lo[a], box.hi[a])
                           for a in range(dim)]
                hi_face = list(lo_face)
                lo_face[d] = slice(box.lo[d], box.lo[d] + 1)
                hi_face[d] = slice(box.hi[d], box.hi[d] + 1)
                # (level-0 lower-face arrays index interface faces
                # identically to the complete-face arrays of l >= 1)
                fc_lo = F[d][tuple(lo_face)]
                fc_hi = F[d][tuple(hi_face)]
                nb_lo = list(lo_face)
                nb_lo[d] = slice(box.lo[d] - 1, box.lo[d])
                nb_hi = list(hi_face)
                nb_hi[d] = slice(box.hi[d], box.hi[d] + 1)
                Ql = Ql.at[tuple(nb_lo)].add(
                    (-dt / g.dx[d]) * (favg_lo - fc_lo))
                Ql = Ql.at[tuple(nb_hi)].add(
                    (dt / g.dx[d]) * (favg_hi - fc_hi))
            Qs[l] = self._sync(Ql)

        slabs = None if l == 0 else self._bdry_slabs(F)
        return Qs, slabs

    # -- public API -----------------------------------------------------
    def step(self, Qs: Sequence[Array], dt: float) -> Tuple[Array, ...]:
        out, _ = self._advance_level(0, list(Qs), None, dt)
        return tuple(out)

    def total(self, Qs: Sequence[Array]) -> Array:
        """Composite conserved integral: uncovered cells per level +
        the full finest level."""
        acc = jnp.asarray(0.0, dtype=self.dtype)
        for l, spec in enumerate(self.levels):
            g = spec.grid
            vol = float(np.prod(g.dx))
            Q = Qs[l]
            if l + 1 < self.L:
                box = self.levels[l + 1].box
                mask = np.ones(g.n, dtype=bool)
                mask[tuple(np.s_[box.lo[a]:box.hi[a]]
                           for a in range(g.dim))] = False
                acc = acc + vol * jnp.sum(jnp.where(jnp.asarray(mask),
                                                    Q, 0.0))
            else:
                acc = acc + vol * jnp.sum(Q)
        return acc
