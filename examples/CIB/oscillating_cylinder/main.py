"""Oscillating-cylinder CIB driver: a rigid disc driven with
prescribed U(t) = V0 cos(2 pi t / T) through the constraint
(prescribed-kinematics) solve — quasi-static Stokes, so the required
force tracks the velocity in phase; on the walled enclosure the
confinement raises the resistance over the periodic frame (reference:
the CIB prescribed-motion examples, CIBMethod::solve_constraint).

Run:  python examples/CIB/oscillating_cylinder/main.py [input2d]
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), *[".."] * 3))

from ibamr_tpu.utils.backend_guard import auto_backend  # noqa: E402

auto_backend()

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from ibamr_tpu.grid import StaggeredGrid  # noqa: E402
from ibamr_tpu.integrators import cib  # noqa: E402
from ibamr_tpu.utils import MetricsLogger, TimerManager, \
    parse_input_file  # noqa: E402


def main(argv):
    input_path = argv[1] if len(argv) > 1 else \
        os.path.join(os.path.dirname(__file__), "input2d")
    db = parse_input_file(input_path)
    main_db = db.get_database("Main")
    geom = db.get_database("CartesianGeometry")
    cdb = db.get_database("CIBMethod")
    body = db.get_database("Body")
    osc = db.get_database("Oscillation")

    grid = StaggeredGrid(
        n=tuple(geom.get_int_array("n_cells")),
        x_lo=tuple(geom.get_float_array("x_lo")),
        x_up=tuple(geom.get_float_array("x_up")))
    cx, cy = body.get_float_array("center")
    m = body.get_int("n_markers")
    # runtime dtype: f64 under JAX_ENABLE_X64, else f32 (requesting
    # f64 in an f32 runtime truncates silently and a too-tight CG
    # tolerance becomes unreachable)
    dtype = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32
    X = cib.make_disc((cx, cy), body.get_float("radius"), m,
                      dtype=dtype)
    bodies = cib.RigidBodies(body_id=jnp.zeros(m, dtype=jnp.int32),
                             n_bodies=1)
    method = cib.CIBMethod(
        grid, bodies, mu=cdb.get_float("mu"),
        cg_tol=cdb.get_float("cg_tol", 1e-8),
        cg_maxiter=cdb.get_int("cg_maxiter", 300),
        domain=cdb.get_string("domain", "periodic"))

    V0 = osc.get_float("V0")
    T = osc.get_float("period")
    spp = osc.get_int("steps_per_period")
    nsteps = osc.get_int("num_periods") * spp
    dt = T / spp

    solve = jax.jit(lambda Xa, U: method.solve_constraint(Xa, U))
    metrics = MetricsLogger(main_db.get_string(
        "log_jsonl", "oscillating_cylinder_metrics.jsonl"))
    timers = TimerManager()

    # quasi-static: the disc oscillates about its center; each step
    # solves the prescribed-kinematics problem at the current phase
    t = 0.0
    amp = V0 * T / (2.0 * np.pi)
    for k in range(nsteps):
        t = (k + 0.5) * dt
        u = V0 * np.cos(2.0 * np.pi * t / T)
        xoff = amp * np.sin(2.0 * np.pi * t / T)
        Xk = X + jnp.asarray([xoff, 0.0])
        U = jnp.asarray([[u, 0.0, 0.0]], dtype=dtype)
        with timers.scope("constraint_solve"):
            lam, FT, info = solve(Xk, U)
            jax.block_until_ready(FT)
        R_eff = float(FT[0, 0]) / u if abs(u) > 1e-12 else float("nan")
        metrics.log({"step": k + 1, "t": t, "u": float(u),
                     "fx": float(FT[0, 0]), "fy": float(FT[0, 1]),
                     "torque": float(FT[0, 2]),
                     "R_eff": R_eff,
                     "converged": bool(info.converged)})
        print(f"step {k + 1}: t={t:.3f} u={u:+.3f} "
              f"Fx={float(FT[0, 0]):+.4f} R_eff={R_eff:.3f}")
    timers.report()


if __name__ == "__main__":
    main(sys.argv)
