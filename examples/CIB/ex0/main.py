"""CIB ex0-equivalent driver: rigid disc sedimenting in periodic Stokes
flow via the constraint/mobility formulation (reference:
examples/CIB/ex0 main.cpp + input2d — CIBMethod + CIBMobilitySolver).

Run:  python examples/CIB/ex0/main.py [input2d]
"""

import os
import sys

import jax
import jax.numpy as jnp

sys.path.insert(0, os.path.join(os.path.dirname(__file__), *[".."] * 3))

# backend guard BEFORE any jax compute: honors JAX_PLATFORMS=cpu
# (defeating the axon sitecustomize override) and probes the TPU
# relay with a timeout instead of hanging when it is down
from ibamr_tpu.utils.backend_guard import auto_backend  # noqa: E402

auto_backend()

import numpy as np  # noqa: E402

from ibamr_tpu.grid import StaggeredGrid  # noqa: E402
from ibamr_tpu.integrators import cib  # noqa: E402
from ibamr_tpu.utils import MetricsLogger, TimerManager, parse_input_file  # noqa: E402


def main(argv):
    input_path = argv[1] if len(argv) > 1 else \
        os.path.join(os.path.dirname(__file__), "input2d")
    db = parse_input_file(input_path)
    main_db = db.get_database("Main")
    geom = db.get_database("CartesianGeometry")
    cib_db = db.get_database("CIBMethod")
    body_db = db.get_database("Body")
    ts = db.get_database("TimeStepping")

    grid = StaggeredGrid(n=tuple(geom.get_int_array("n_cells")),
                         x_lo=tuple(geom.get_float_array("x_lo")),
                         x_up=tuple(geom.get_float_array("x_up")))
    nm = body_db.get_int("num_markers")
    X = cib.make_disc(tuple(body_db.get_float_array("center")),
                      body_db.get_float("radius"), nm)
    bodies = cib.RigidBodies(body_id=jnp.zeros(nm, dtype=jnp.int32),
                             n_bodies=1)
    method = cib.CIBMethod(
        grid, bodies, mu=cib_db.get_float("mu", 1.0),
        kernel=cib_db.get_string("delta_fcn", "IB_4"),
        cg_tol=cib_db.get_float("cg_tol", 1e-9),
        cg_maxiter=cib_db.get_int("cg_maxiter", 400))

    F = body_db.get_float_array("force")
    tau = body_db.get_float("torque", 0.0)
    FT = jnp.asarray([[F[0], F[1], tau]], dtype=X.dtype)

    dt = ts.get_float("dt")
    num_steps = ts.get_int("num_steps")
    viz_dir = main_db.get_string("viz_dirname", "viz_cib")
    os.makedirs(viz_dir, exist_ok=True)
    metrics = MetricsLogger(main_db.get_string("log_file", "") or None)
    timers = TimerManager()

    step = jax.jit(lambda x: method.step(x, FT, dt))
    dump = main_db.get_int("viz_dump_interval", 0)
    for k in range(num_steps):
        with timers.scope("CIB::step"):
            X, U, info = step(X)
            jax.block_until_ready(X)
        cent = cib.body_centroids(X, bodies)
        metrics.log({"step": k + 1, "t": (k + 1) * dt,
                     "cg_converged": bool(info.converged),
                     "cg_iters": int(info.max_iters),
                     "centroid": np.asarray(cent[0]).tolist(),
                     "U": np.asarray(U[0]).tolist()})
        if dump and (k + 1) % dump == 0:
            np.save(os.path.join(viz_dir, f"markers_{k + 1:05d}.npy"),
                    np.asarray(X))
    metrics.close()
    print(timers.report())
    cent = cib.body_centroids(X, bodies)
    print(f"final centroid: {np.asarray(cent[0])}")
    return X


if __name__ == "__main__":
    main(sys.argv)
