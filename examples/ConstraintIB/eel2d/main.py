"""Undulatory-swimmer driver: a slender body self-propelling by a
prescribed traveling-wave gait under the ConstraintIB momentum
projection (reference: the ConstraintIB eel2d example — prescribed
deformational kinematics with the rigid component projected out, free
translation recovered from momentum conservation; Bhalla et al. 2013).
The body's lateral deformation velocity follows a backward-traveling
wave with a tail-growing amplitude envelope; thrust emerges from the
fluid coupling alone, and the swimmer accelerates opposite the wave.
COM trajectory and swim speed land in the metrics JSONL.

Run:  python examples/ConstraintIB/eel2d/main.py [input2d]
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), *[".."] * 3))

from ibamr_tpu.utils.backend_guard import auto_backend  # noqa: E402

auto_backend()

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from ibamr_tpu.grid import StaggeredGrid  # noqa: E402
from ibamr_tpu.integrators.cib import RigidBodies  # noqa: E402
from ibamr_tpu.integrators.constraint_ib import (  # noqa: E402
    ConstraintIBMethod, advance_constraint_ib)
from ibamr_tpu.integrators.ins import INSStaggeredIntegrator  # noqa: E402
from ibamr_tpu.utils import MetricsLogger, TimerManager, \
    parse_input_file  # noqa: E402


def build_eel(eel, dx, dtype=jnp.float32):
    """Slender marker body: length L, thickness h, spacing ~dx/2."""
    L = eel.get_float("length")
    h = eel.get_float("thickness")
    cx, cy = eel.get_float_array("center")
    sp = dx / 2.0
    ns = max(2, int(round(L / sp)) + 1)
    nt = max(2, int(round(h / sp)) + 1)
    s = np.linspace(0.0, L, ns)
    t = np.linspace(-h / 2, h / 2, nt)
    S, T = np.meshgrid(s, t, indexing="ij")
    X0 = np.stack([cx - L / 2 + S.ravel(), cy + T.ravel()], axis=1)
    return (jnp.asarray(X0, dtype=dtype),
            jnp.asarray(S.ravel(), dtype=dtype), L)


def make_gait(eel, s, L):
    """Backward-traveling-wave lateral velocity with a linear
    amplitude envelope A(s) = A0 * s / L (head quiet, tail driving) —
    the standard anguilliform parameterization."""
    A0 = eel.get_float("amplitude")
    lam = eel.get_float("wavelength")
    omega = 2.0 * np.pi * eel.get_float("frequency")
    k = 2.0 * np.pi / lam

    def deformation_fn(t, X):
        phase = k * s - omega * t
        uy = -(A0 * s / L) * omega * jnp.cos(phase)
        return jnp.stack([jnp.zeros_like(uy), uy], axis=1)

    return deformation_fn


def main(argv):
    input_path = argv[1] if len(argv) > 1 else \
        os.path.join(os.path.dirname(__file__), "input2d")
    db = parse_input_file(input_path)
    main_db = db.get_database("Main")
    geo = db.get_database("CartesianGeometry")
    idb = db.get_database("INSStaggeredHierarchyIntegrator")
    eel = db.get_database("Eel")

    n = tuple(geo.get_int_array("n"))
    grid = StaggeredGrid(n=n, x_lo=tuple(geo.get_float_array("x_lo")),
                         x_up=tuple(geo.get_float_array("x_up")))
    ins = INSStaggeredIntegrator(grid, rho=idb.get_float("rho", 1.0),
                                 mu=idb.get_float("mu"))
    X0, s, L = build_eel(eel, grid.dx[0], dtype=ins.dtype)
    bodies = RigidBodies(body_id=jnp.zeros(X0.shape[0],
                                           dtype=jnp.int32), n_bodies=1)
    method = ConstraintIBMethod(ins, bodies,
                                deformation_fn=make_gait(eel, s, L))
    st = method.initialize(X0)

    metrics = MetricsLogger(main_db.get_string("log_jsonl",
                                               "eel2d_metrics.jsonl"))
    timers = TimerManager()
    dt = idb.get_float("dt")
    num_steps = idb.get_int("num_steps")
    chunk = main_db.get_int("log_interval", 50)

    com0 = float(jnp.mean(st.X[:, 0]))
    k = 0
    while k < num_steps:
        m = min(chunk, num_steps - k)
        with timers.scope("advance"):
            st = advance_constraint_ib(method, st, dt, m)
            jax.block_until_ready(st.X)
        k += m
        com = [float(jnp.mean(st.X[:, 0])), float(jnp.mean(st.X[:, 1]))]
        metrics.log({"step": k, "t": float(st.ins.t),
                     "com_x": com[0], "com_y": com[1],
                     "swim_dx": com[0] - com0,
                     "U_body": [float(v) for v in st.U_body[0]]})
        print(f"step {k}: COM x {com[0]:.4f} (swim dx "
              f"{com[0] - com0:+.4f}), U_body "
              f"{[round(float(v), 4) for v in st.U_body[0]]}")
    print(timers.report())


if __name__ == "__main__":
    main(sys.argv)
