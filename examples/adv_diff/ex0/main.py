"""IB + adv-diff driver: passive scalar released at the immersed membrane
markers (reference parity: AdvDiffSemiImplicitHierarchyIntegrator P19
registered with the IB/INS integrator, marker sources a la
IBStandardSourceGen P14 — SURVEY.md §2.2).

Run:  python examples/adv_diff/ex0/main.py [input2d]
"""

import os
import sys

import jax
import jax.numpy as jnp

sys.path.insert(0, os.path.join(os.path.dirname(__file__), *[".."] * 3))

# backend guard BEFORE any jax compute: honors JAX_PLATFORMS=cpu
# (defeating the axon sitecustomize override) and probes the TPU
# relay with a timeout instead of hanging when it is down
from ibamr_tpu.utils.backend_guard import auto_backend  # noqa: E402

auto_backend()

import numpy as np  # noqa: E402

from ibamr_tpu.integrators.adv_diff import (  # noqa: E402
    AdvDiffSemiImplicitIntegrator, TransportedQuantity)
from ibamr_tpu.integrators.ib import polygon_area  # noqa: E402
from ibamr_tpu.models.membrane2d import build_membrane_example  # noqa: E402
from ibamr_tpu.ops import interaction  # noqa: E402
from ibamr_tpu.utils import MetricsLogger, TimerManager, parse_input_file  # noqa: E402


def main(argv):
    input_path = argv[1] if len(argv) > 1 else \
        os.path.join(os.path.dirname(__file__), "input2d")
    db = parse_input_file(input_path)
    main_db = db.get_database("Main")
    ins_db = db.get_database("INSStaggeredHierarchyIntegrator")
    ad_db = db.get_database_with_default(
        "AdvDiffSemiImplicitHierarchyIntegrator")

    integ, state = build_membrane_example(input_db=db, dtype=jnp.float32)
    grid = integ.ins.grid
    kernel = integ.ib.kernel

    adv = AdvDiffSemiImplicitIntegrator(
        grid,
        [TransportedQuantity(
            "C", kappa=ad_db.get_float("kappa", 1e-3),
            convective_op_type=ad_db.get_string("convective_op_type",
                                                "upwind"))],
        dtype=jnp.float32)
    ad_state = adv.initialize()
    strength = ad_db.get_float("source_strength", 1.0)

    def coupled_step(ib_state, ad_state, dt):
        """One IB step, then the scalar advected by the new velocity with
        a source spread from the markers (unit strength per marker)."""
        ib_new = integ.step(ib_state, dt)
        src_markers = jnp.full((ib_new.X.shape[0],), strength,
                               dtype=jnp.float32)
        src = interaction.spread(src_markers, grid, ib_new.X,
                                 centering="cell", kernel=kernel,
                                 weights=ib_new.mask)
        ad_new = adv.step(ad_state, dt, u=ib_new.ins.u, sources=[src])
        return ib_new, ad_new

    step_fn = jax.jit(coupled_step)

    dt = ins_db.get_float("dt")
    num_steps = ins_db.get_int("num_steps")
    viz_int = main_db.get_int("viz_dump_interval", 0)
    viz_dir = main_db.get_string("viz_dirname", "viz_adv_diff")
    os.makedirs(viz_dir, exist_ok=True)

    tm = TimerManager.instance()
    with MetricsLogger(main_db.get_string("log_file"), echo=True) as metrics:
        step = 0
        while step < num_steps:
            chunk = min(viz_int or 25, num_steps - step)
            with tm.scope("IBAdvDiff::advanceHierarchy"):
                for _ in range(chunk):
                    state, ad_state = step_fn(state, ad_state, dt)
                jax.block_until_ready(ad_state.Q)
            step += chunk
            metrics.log({
                "step": step,
                "t": state.ins.t,
                "area": polygon_area(state.X),
                "scalar_total": adv.total(ad_state),
                "scalar_max": jnp.max(ad_state.Q[0]),
                "max_div": integ.ins.max_divergence(state.ins),
            })
            if viz_int:
                np.save(os.path.join(viz_dir, f"scalar.{step:06d}.npy"),
                        np.asarray(ad_state.Q[0]))
    print(tm.report())
    return state, ad_state


if __name__ == "__main__":
    main(sys.argv)
