"""Lid-driven-cavity driver: the classic wall-bounded NS validation
(reference: the navier_stokes lid-cavity examples over the staggered
INS integrator with physical-wall Dirichlet BCs; Ghia, Ghia & Shin
1982 for the benchmark profiles). All four walls are no-slip; the top
lid moves at U_lid. The u(x=0.5, y) centerline profile and the
primary-vortex strength land in the metrics JSONL for comparison
against the Ghia table (pinned at Re=100 by
tests/test_ins_ppm_walls.py::test_lid_driven_cavity_re100_ghia).

Run:  python examples/navier_stokes/cavity2d/main.py [input2d]
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), *[".."] * 3))

from ibamr_tpu.utils.backend_guard import auto_backend  # noqa: E402

auto_backend()

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from ibamr_tpu.grid import StaggeredGrid  # noqa: E402
from ibamr_tpu.integrators.ins import (INSStaggeredIntegrator,  # noqa: E402
                                       advance)
from ibamr_tpu.io.vtk import write_vti  # noqa: E402
from ibamr_tpu.utils import MetricsLogger, TimerManager, \
    parse_input_file  # noqa: E402


def main(argv):
    input_path = argv[1] if len(argv) > 1 else \
        os.path.join(os.path.dirname(__file__), "input2d")
    db = parse_input_file(input_path)
    main_db = db.get_database("Main")
    geo = db.get_database("CartesianGeometry")
    idb = db.get_database("INSStaggeredHierarchyIntegrator")

    n = tuple(geo.get_int_array("n"))
    grid = StaggeredGrid(n=n, x_lo=tuple(geo.get_float_array("x_lo")),
                         x_up=tuple(geo.get_float_array("x_up")))
    u_lid = idb.get_float("U_lid", 1.0)
    integ = INSStaggeredIntegrator(
        grid, rho=idb.get_float("rho", 1.0), mu=idb.get_float("mu"),
        convective_op_type=idb.get_string("convective_op_type", "ppm"),
        wall_axes=(True, True),
        # component 0's tangential velocity on the hi wall of axis 1:
        # the moving lid
        wall_tangential={(0, 1, 1): u_lid})
    st = integ.initialize()

    viz_dir = main_db.get_string("viz_dirname", "viz_cavity2d")
    os.makedirs(viz_dir, exist_ok=True)
    metrics = MetricsLogger(main_db.get_string("log_jsonl",
                                               "cavity2d_metrics.jsonl"))
    timers = TimerManager()
    dt = idb.get_float("dt")
    num_steps = idb.get_int("num_steps")
    viz_int = main_db.get_int("viz_dump_interval", 0)
    chunk = main_db.get_int("log_interval", viz_int if viz_int else
                            num_steps)

    k = 0
    while k < num_steps:
        m = min(chunk, num_steps - k)
        with timers.scope("advance"):
            st = advance(integ, st, dt, m)
            jax.block_until_ready(st.u[0])
        k += m
        uc = np.asarray(st.u[0][n[0] // 2, :])
        metrics.log({"step": k, "t": float(st.t),
                     "u_center_min": float(uc.min()),
                     "max_div": float(integ.max_divergence(st))})
        print(f"step {k}: primary-vortex u_min {uc.min():.5f} "
              f"(Ghia Re=100: -0.21090), max div "
              f"{float(integ.max_divergence(st)):.1e}")
        if viz_int and k % viz_int == 0:
            write_vti(os.path.join(viz_dir, f"cavity_{k:05d}.vti"),
                      grid, {"p": np.asarray(st.p)})
    # final centerline profile for offline Ghia comparison
    metrics.log({"step": k, "centerline_u":
                 [float(v) for v in np.asarray(st.u[0][n[0] // 2, :])]})
    print(timers.report())


if __name__ == "__main__":
    main(sys.argv)
