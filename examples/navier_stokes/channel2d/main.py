"""Channel-flow driver: inflow -> developed Poiseuille -> open outflow.

Reference parity: the inflow/outflow INS example family (P2/P3 with
INSProjectionBcCoef-style open boundaries). Exercises the coupled
staggered-Stokes saddle solve (solvers.stokes) with explicit upwind
convection each step.

Run:  python examples/navier_stokes/channel2d/main.py [input2d]
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), *[".."] * 3))

# backend guard BEFORE any jax compute: honors JAX_PLATFORMS=cpu
# (defeating the axon sitecustomize override) and probes the TPU
# relay with a timeout instead of hanging when it is down
from ibamr_tpu.utils.backend_guard import auto_backend  # noqa: E402

auto_backend()

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from ibamr_tpu.grid import StaggeredGrid  # noqa: E402
from ibamr_tpu.integrators.ins_open import INSOpenIntegrator  # noqa: E402
from ibamr_tpu.io.vtk import write_vti  # noqa: E402
from ibamr_tpu.solvers.stokes import channel_bc  # noqa: E402
from ibamr_tpu.utils import MetricsLogger, TimerManager, \
    parse_input_file  # noqa: E402


def main(argv):
    input_path = argv[1] if len(argv) > 1 else \
        os.path.join(os.path.dirname(__file__), "input2d")
    db = parse_input_file(input_path)
    main_db = db.get_database("Main")
    geo = db.get_database("CartesianGeometry")
    ins_db = db.get_database("INSOpenIntegrator")

    n = tuple(geo.get_int_array("n"))
    x_lo = tuple(geo.get_float_array("x_lo"))
    x_up = tuple(geo.get_float_array("x_up"))
    grid = StaggeredGrid(n=n, x_lo=x_lo, x_up=x_up)
    H = x_up[1] - x_lo[1]
    dy = H / n[1]
    U = ins_db.get_float("U_max", 1.0)
    y = (np.arange(n[1]) + 0.5) * dy
    profile = 4.0 * U * y * (H - y) / H ** 2

    integ = INSOpenIntegrator(
        n, grid.dx, channel_bc(2),
        mu=ins_db.get_float("mu"), dt=ins_db.get_float("dt"),
        rho=ins_db.get_float("rho", 1.0),
        bdry={(0, 0, 0): jnp.asarray(profile)[None, :], (1, 0, 0): 0.0},
        tol=ins_db.get_float("solver_tol", 1e-8))
    state = integ.initialize()

    viz_dir = main_db.get_string("viz_dirname", "viz_channel2d")
    os.makedirs(viz_dir, exist_ok=True)
    metrics = MetricsLogger(main_db.get_string("log_jsonl",
                                               "channel2d_metrics.jsonl"))
    timers = TimerManager()
    step = jax.jit(integ.step)
    num_steps = ins_db.get_int("num_steps")
    viz_int = main_db.get_int("viz_dump_interval", 0)

    for k in range(num_steps):
        with timers.scope("step"):
            state = step(state)
        if viz_int and (k + 1) % viz_int == 0:
            jax.block_until_ready(state.u[0])
            u_cc = tuple(np.asarray(c) for c in integ._to_cells(state.u))
            write_vti(os.path.join(viz_dir, f"u_{k + 1:05d}.vti"), grid,
                      {"u": u_cc[0], "v": u_cc[1],
                       "p": np.asarray(state.p)})
            flux = float(np.asarray(state.u[0]).sum(axis=1)[-1] * dy)
            metrics.log({"step": k + 1, "t": float(state.t),
                         "outflow_flux": flux,
                         "max_div": float(integ.max_divergence(state))})
            print(f"step {k + 1}: outflow flux {flux:.5f}")

    print(timers.report())
    un = np.asarray(state.u[0])
    err = float(np.max(np.abs(un[3 * n[0] // 4, :] - profile)))
    print(f"developed-profile error vs Poiseuille: {err:.2e}")


if __name__ == "__main__":
    main(sys.argv)
