"""Falling-drop driver: two-phase VC-INS at density ratio 1000
(reference: the INSVCStaggeredHierarchyIntegrator multiphase examples).
Exercises the level-set coupling, CSF surface tension, gravity on the
heavy phase, and the multigrid-preconditioned variable-density
projection.

Run:  python examples/multiphase/falling_drop/main.py [input2d]
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), *[".."] * 3))

# backend guard BEFORE any jax compute
from ibamr_tpu.utils.backend_guard import auto_backend  # noqa: E402

auto_backend()

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from ibamr_tpu.grid import StaggeredGrid  # noqa: E402
from ibamr_tpu.integrators.ins_vc import (INSVCStaggeredIntegrator,  # noqa: E402
                                          advance_vc)
from ibamr_tpu.io.vtk import write_vti  # noqa: E402
from ibamr_tpu.ops import stencils  # noqa: E402
from ibamr_tpu.utils import MetricsLogger, TimerManager, \
    parse_input_file  # noqa: E402


def main(argv):
    input_path = argv[1] if len(argv) > 1 else \
        os.path.join(os.path.dirname(__file__), "input2d")
    db = parse_input_file(input_path)
    main_db = db.get_database("Main")
    geo = db.get_database("CartesianGeometry")
    vc = db.get_database("INSVCStaggeredHierarchyIntegrator")

    n = tuple(geo.get_int_array("n"))
    grid = StaggeredGrid(n=n, x_lo=tuple(geo.get_float_array("x_lo")),
                         x_up=tuple(geo.get_float_array("x_up")))
    # wall_axes = 0, 1 puts PHYSICAL no-slip walls on both sides of the
    # flagged axes (a closed tank) instead of the periodic default —
    # the wall-bounded P22 configuration (input2d.walled)
    wall_axes = tuple(bool(v) for v in
                      vc.get_int_array("wall_axes", [0] * len(n)))
    integ = INSVCStaggeredIntegrator(
        grid, rho0=vc.get_float("rho0"), rho1=vc.get_float("rho1"),
        mu0=vc.get_float("mu0"), mu1=vc.get_float("mu1"),
        sigma=vc.get_float("sigma", 0.0),
        gravity=(0.0, vc.get_float("gravity_y", 0.0)),
        wall_axes=wall_axes if any(wall_axes) else None,
        cg_tol=vc.get_float("cg_tol", 1.0e-5))   # f32 floor

    cx, cy = vc.get_float_array("drop_center")
    r0 = vc.get_float("drop_radius")
    x = (np.arange(n[0]) + 0.5) * grid.dx[0]
    y = (np.arange(n[1]) + 0.5) * grid.dx[1]
    X, Y = np.meshgrid(x, y, indexing="ij")
    phi0 = jnp.asarray(r0 - np.sqrt((X - cx) ** 2 + (Y - cy) ** 2),
                       dtype=jnp.float32)
    st = integ.initialize(phi0)
    vol0 = float(integ.heavy_phase_volume(st))

    viz_dir = main_db.get_string("viz_dirname", "viz_falling_drop")
    os.makedirs(viz_dir, exist_ok=True)
    metrics = MetricsLogger(main_db.get_string("log_jsonl",
                                               "falling_drop_metrics.jsonl"))
    timers = TimerManager()
    dt = vc.get_float("dt")
    num_steps = vc.get_int("num_steps")
    viz_int = main_db.get_int("viz_dump_interval", 0)
    chunk = viz_int if viz_int else num_steps

    k = 0
    while k < num_steps:
        m = min(chunk, num_steps - k)
        with timers.scope("advance"):
            st = advance_vc(integ, st, dt, m)
            jax.block_until_ready(st.u[0])
        k += m
        vol = float(integ.heavy_phase_volume(st))
        div = float(jnp.max(jnp.abs(stencils.divergence(st.u, grid.dx))))
        metrics.log({"step": k, "t": float(st.t),
                     "volume_drift": abs(vol - vol0) / vol0,
                     "max_div": div})
        print(f"step {k}: volume drift {abs(vol - vol0) / vol0:.2e}, "
              f"max div {div:.1e}")
        if viz_int:
            write_vti(os.path.join(viz_dir, f"drop_{k:05d}.vti"), grid,
                      {"phi": np.asarray(st.phi),
                       "p": np.asarray(st.p)})
    print(timers.report())


if __name__ == "__main__":
    main(sys.argv)
