"""Dam-break driver: a water column collapsing in a walled tank under
gravity — the canonical two-phase VC-INS validation (reference: the
multiphase dam-break examples over INSVCStaggeredHierarchyIntegrator;
Martin & Moyce 1952 for the surge-front scaling). Exercises the
wall-bounded variable-coefficient projection, the level-set transport
with reinitialization, and gravity at density ratio ~1000. The surge
front x(t) along the tank floor lands in the metrics JSONL: after the
initial transient it advances at ~2*sqrt(g*h0) (the shallow-water
bound Martin & Moyce's data approach from below).

Run:  python examples/multiphase/dam_break/main.py [input2d]
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), *[".."] * 3))

from ibamr_tpu.utils.backend_guard import auto_backend  # noqa: E402

auto_backend()

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from ibamr_tpu.grid import StaggeredGrid  # noqa: E402
from ibamr_tpu.integrators.ins_vc import (INSVCStaggeredIntegrator,  # noqa: E402
                                          advance_vc)
from ibamr_tpu.io.vtk import write_vti  # noqa: E402
from ibamr_tpu.ops import stencils  # noqa: E402
from ibamr_tpu.utils import MetricsLogger, TimerManager, \
    parse_input_file  # noqa: E402
from ibamr_tpu.utils.checkpoint import (restore_checkpoint,  # noqa: E402
                                        save_checkpoint)


def surge_front(phi, grid) -> float:
    """Rightmost x where the heavy phase (phi > 0) touches the floor
    row — the Martin & Moyce front position."""
    floor = np.asarray(phi[:, 0])
    wet = np.nonzero(floor > 0)[0]
    if wet.size == 0:
        return 0.0
    return float((wet.max() + 0.5) * grid.dx[0])


def main(argv):
    """``main.py [input2d] [--restart]``: with ``--restart``, resume
    from the latest checkpoint in Main.restart_dirname and continue to
    num_steps — the RestartManager-style workflow every reference
    example supports."""
    restart = "--restart" in argv
    argv = [a for a in argv if a != "--restart"]
    input_path = argv[1] if len(argv) > 1 else \
        os.path.join(os.path.dirname(__file__), "input2d")
    db = parse_input_file(input_path)
    main_db = db.get_database("Main")
    geo = db.get_database("CartesianGeometry")
    vc = db.get_database("INSVCStaggeredHierarchyIntegrator")

    n = tuple(geo.get_int_array("n"))
    grid = StaggeredGrid(n=n, x_lo=tuple(geo.get_float_array("x_lo")),
                         x_up=tuple(geo.get_float_array("x_up")))
    integ = INSVCStaggeredIntegrator(
        grid, rho0=vc.get_float("rho0"), rho1=vc.get_float("rho1"),
        mu0=vc.get_float("mu0"), mu1=vc.get_float("mu1"),
        sigma=vc.get_float("sigma", 0.0),
        gravity=(0.0, vc.get_float("gravity_y", 0.0)),
        wall_axes=(True, True),          # closed tank: all physical walls
        cg_tol=vc.get_float("cg_tol", 1.0e-5))

    # water column against the left wall: width a, height h0
    a = vc.get_float("column_width")
    h0 = vc.get_float("column_height")
    x = (np.arange(n[0]) + 0.5) * grid.dx[0]
    y = (np.arange(n[1]) + 0.5) * grid.dx[1]
    X, Y = np.meshgrid(x, y, indexing="ij")
    phi0 = jnp.asarray(np.minimum(a - X, h0 - Y), dtype=jnp.float32)
    st = integ.initialize(phi0)
    # restart-invariant drift reference: taken from the fresh t=0
    # state BEFORE any restore
    vol0 = float(integ.heavy_phase_volume(st))

    restart_dir = main_db.get_string("restart_dirname", "restart_dam")
    restart_int = main_db.get_int("restart_interval", 0)
    k = 0
    if restart:
        st, k, _meta = restore_checkpoint(restart_dir, template=st)
        print(f"restarted from {restart_dir} at step {k}")

    viz_dir = main_db.get_string("viz_dirname", "viz_dam_break")
    os.makedirs(viz_dir, exist_ok=True)
    metrics = MetricsLogger(main_db.get_string("log_jsonl",
                                               "dam_break_metrics.jsonl"))
    timers = TimerManager()
    dt = vc.get_float("dt")
    num_steps = vc.get_int("num_steps")
    viz_int = main_db.get_int("viz_dump_interval", 0)
    chunk = main_db.get_int("log_interval", viz_int if viz_int else
                            num_steps)
    if restart_int:
        chunk = min(chunk, restart_int)
    last_ckpt_epoch = k // restart_int if restart_int else 0
    while k < num_steps:
        m = min(chunk, num_steps - k)
        with timers.scope("advance"):
            st = advance_vc(integ, st, dt, m)
            jax.block_until_ready(st.u[0])
        k += m
        vol = float(integ.heavy_phase_volume(st))
        front = surge_front(st.phi, grid)
        div = float(jnp.max(jnp.abs(stencils.divergence(st.u, grid.dx))))
        metrics.log({"step": k, "t": float(st.t), "front": front,
                     "volume_drift": abs(vol - vol0) / vol0,
                     "max_div": div})
        print(f"step {k}: front {front:.3f}, volume drift "
              f"{abs(vol - vol0) / vol0:.2e}, max div {div:.1e}")
        if viz_int and k % viz_int == 0:
            write_vti(os.path.join(viz_dir, f"dam_{k:05d}.vti"), grid,
                      {"phi": np.asarray(st.phi),
                       "p": np.asarray(st.p)})
        if restart_int and k // restart_int > last_ckpt_epoch:
            # epoch-crossing rule: a dump lands whenever the run passes
            # a restart_interval boundary even when log_interval does
            # not divide it (k need not hit an exact multiple)
            last_ckpt_epoch = k // restart_int
            save_checkpoint(restart_dir, st, step=k)
    print(timers.report())


if __name__ == "__main__":
    main(sys.argv)
