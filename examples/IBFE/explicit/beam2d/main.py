"""IBFE cantilever-beam driver: a hyperelastic FE beam clamped to the
channel floor, bending under an inflow (reference: the IBFE flexible-
beam/flag examples — IBFEMethod over an inflow/outflow INS domain with
a tethered base; the clamp is the standard stiff-penalty anchor on the
base nodes). The tip deflection time series and elastic energy land in
the metrics JSONL; at steady state the beam leans downstream with a
deflection set by the Cauchy number.

Run:  python examples/IBFE/explicit/beam2d/main.py [input2d]
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), *[".."] * 4))

from ibamr_tpu.utils.backend_guard import auto_backend  # noqa: E402

auto_backend()

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from ibamr_tpu.fe.fem import neo_hookean  # noqa: E402
from ibamr_tpu.fe.mesh import rect_quad_mesh  # noqa: E402
from ibamr_tpu.integrators.ib_open import (IBOpenIntegrator,  # noqa: E402
                                           advance_ib_open)
from ibamr_tpu.integrators.ibfe import IBFEMethod  # noqa: E402
from ibamr_tpu.integrators.ins_open import INSOpenIntegrator  # noqa: E402
from ibamr_tpu.solvers.stokes import channel_bc  # noqa: E402
from ibamr_tpu.utils import MetricsLogger, TimerManager, \
    parse_input_file  # noqa: E402


def main(argv):
    input_path = argv[1] if len(argv) > 1 else \
        os.path.join(os.path.dirname(__file__), "input2d")
    db = parse_input_file(input_path)
    main_db = db.get_database("Main")
    geo = db.get_database("CartesianGeometry")
    idb = db.get_database("INSOpenIntegrator")
    bm = db.get_database("Beam")

    n = tuple(geo.get_int_array("n"))
    x_lo = tuple(geo.get_float_array("x_lo"))
    x_up = tuple(geo.get_float_array("x_up"))
    dx = tuple((u - l) / m for u, l, m in zip(x_up, x_lo, n))
    dt = idb.get_float("dt")
    U0 = idb.get_float("U0")
    ins = INSOpenIntegrator(n, dx, channel_bc(2),
                            mu=idb.get_float("mu"), dt=dt,
                            rho=idb.get_float("rho", 1.0),
                            bdry={(0, 0, 0): U0},
                            tol=idb.get_float("tol", 1.0e-6),
                            dtype=jnp.float32)  # production dtype

    # clamped-base beam: width w centered at base_x, base row held at
    # height base_y. base_y must keep delta-support clearance (>= 2
    # cells for IB_4) from the floor: the open-boundary layout bridge
    # is exact only when no kernel footprint touches the domain faces
    # (ops/stencils.py mac_complete_from_periodic), so the beam stands
    # on a short mounting gap like the reference's post-mounted
    # structures rather than flush against y = 0.
    w = bm.get_float("width")
    H = bm.get_float("height")
    bx = bm.get_float("base_x")
    by = bm.get_float("base_y", 0.1)
    if by < 3.0 * dx[1]:
        raise ValueError(
            f"Beam.base_y = {by} is within the IB_4 delta support of "
            f"the floor (need >= {3.0 * dx[1]:.4f}); raise base_y or "
            "refine the grid")
    nx_el = bm.get_int("nx_elems", 2)
    ny_el = bm.get_int("ny_elems", 12)
    mesh = rect_quad_mesh(nx_el, ny_el, x_lo=(bx - w / 2, by),
                          x_up=(bx + w / 2, by + H))
    X0 = jnp.asarray(mesh.nodes, dtype=jnp.float32)
    base = jnp.asarray(mesh.nodes[:, 1] <= by + 1e-9,
                       dtype=jnp.float32)
    k_anchor = bm.get_float("k_anchor")

    def tether(x, t):
        # stiff-penalty clamp of the base row (the reference's tethered
        # IBFE boundary condition)
        return -k_anchor * (x - X0) * base[:, None]

    fe = IBFEMethod(mesh, neo_hookean(bm.get_float("shear_modulus"),
                                      bm.get_float("bulk_modulus")),
                    kernel="IB_4", body_force=tether)
    integ = IBOpenIntegrator(ins, fe, x_lo=x_lo)
    st = integ.initialize(X0)

    tip = int(np.argmax(mesh.nodes[:, 1] +
                        1e-6 * np.abs(mesh.nodes[:, 0] - bx)))
    metrics = MetricsLogger(main_db.get_string("log_jsonl",
                                               "beam2d_metrics.jsonl"))
    timers = TimerManager()
    num_steps = idb.get_int("num_steps")
    chunk = main_db.get_int("log_interval", 50)

    k = 0
    while k < num_steps:
        m = min(chunk, num_steps - k)
        with timers.scope("advance"):
            st = advance_ib_open(integ, st, m)
            jax.block_until_ready(st.X)
        k += m
        defl = float(st.X[tip, 0] - X0[tip, 0])
        E = float(fe.energy(st.X))
        metrics.log({"step": k, "tip_deflection": defl,
                     "tip_y": float(st.X[tip, 1]),
                     "elastic_energy": E})
        print(f"step {k}: tip deflection {defl:+.4f}, energy {E:.4g}")
    print(timers.report())


if __name__ == "__main__":
    main(sys.argv)
