"""IBFE ex0-equivalent driver: stretched hyperelastic FE disc relaxing in
periodic incompressible flow (reference: examples/IBFE/explicit/ex0
main.cpp + input2d — IBFEMethod with a neo-Hookean solid).

Run:  python examples/IBFE/explicit/ex0/main.py [input2d]
"""

import os
import sys

import jax

sys.path.insert(0, os.path.join(os.path.dirname(__file__), *[".."] * 4))

# backend guard BEFORE any jax compute: honors JAX_PLATFORMS=cpu
# (defeating the axon sitecustomize override) and probes the TPU
# relay with a timeout instead of hanging when it is down
from ibamr_tpu.utils.backend_guard import auto_backend  # noqa: E402

auto_backend()

import numpy as np  # noqa: E402

from ibamr_tpu.models.fe_disc2d import build_fe_disc_example  # noqa: E402
from ibamr_tpu.utils import MetricsLogger, TimerManager, parse_input_file  # noqa: E402


def main(argv):
    input_path = argv[1] if len(argv) > 1 else \
        os.path.join(os.path.dirname(__file__), "input2d")
    db = parse_input_file(input_path)
    main_db = db.get_database("Main")
    ts = db.get_database("TimeStepping")

    integ, state = build_fe_disc_example(input_db=db)
    fe = integ.ib

    dt = ts.get_float("dt")
    num_steps = ts.get_int("num_steps")
    viz_dir = main_db.get_string("viz_dirname", "viz_ibfe")
    os.makedirs(viz_dir, exist_ok=True)
    metrics = MetricsLogger(main_db.get_string("log_file", "") or None)
    timers = TimerManager()

    step = jax.jit(lambda s: integ.step(s, dt))
    dump = main_db.get_int("viz_dump_interval", 0)
    A0 = float(fe.current_volume(state.X))
    for k in range(num_steps):
        with timers.scope("IBFE::step"):
            state = step(state)
            jax.block_until_ready(state.X)
        if (k + 1) % 10 == 0 or k == 0:
            E = float(fe.energy(state.X))
            A = float(fe.current_volume(state.X))
            metrics.log({"step": k + 1, "t": (k + 1) * dt,
                         "elastic_energy": E,
                         "area": A, "area_drift": (A - A0) / A0})
        if dump and (k + 1) % dump == 0:
            np.save(os.path.join(viz_dir, f"nodes_{k + 1:05d}.npy"),
                    np.asarray(state.X))
    metrics.close()
    print(timers.report())
    print(f"final elastic energy: {float(fe.energy(state.X)):.6g}, "
          f"area drift: {(float(fe.current_volume(state.X)) - A0) / A0:.3e}")
    return state


if __name__ == "__main__":
    main(sys.argv)
