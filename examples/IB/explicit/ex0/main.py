"""ex0-equivalent driver: 2D periodic elastic membrane in incompressible
flow (reference: examples/IB/explicit/ex0 main.cpp + input2d).

Run:  python examples/IB/explicit/ex0/main.py [input2d] [restart_dir step]
"""

import os
import sys

import jax
import jax.numpy as jnp

sys.path.insert(0, os.path.join(os.path.dirname(__file__), *[".."] * 4))

# backend guard BEFORE any jax compute: honors JAX_PLATFORMS=cpu
# (defeating the axon sitecustomize override) and probes the TPU
# relay with a timeout instead of hanging when it is down
from ibamr_tpu.utils.backend_guard import auto_backend  # noqa: E402

auto_backend()

import numpy as np  # noqa: E402

from ibamr_tpu.integrators.ib import advance_ib, polygon_area  # noqa: E402
from ibamr_tpu.models.membrane2d import build_membrane_example  # noqa: E402
from ibamr_tpu.utils import MetricsLogger, TimerManager, parse_input_file  # noqa: E402
from ibamr_tpu.utils.checkpoint import restore_checkpoint, save_checkpoint  # noqa: E402


def main(argv):
    input_path = argv[1] if len(argv) > 1 else \
        os.path.join(os.path.dirname(__file__), "input2d")
    db = parse_input_file(input_path)
    main_db = db.get_database("Main")
    ins_db = db.get_database("INSStaggeredHierarchyIntegrator")

    integ, state = build_membrane_example(input_db=db, dtype=jnp.float32)

    # optional restart: main.py input2d <restart_dir> <step>
    start_step = 0
    if len(argv) > 3:
        state, start_step, _ = restore_checkpoint(argv[2], state,
                                                  step=int(argv[3]))
        print(f"restarted from {argv[2]} at step {start_step}")

    dt = ins_db.get_float("dt")
    num_steps = ins_db.get_int("num_steps")
    viz_int = main_db.get_int("viz_dump_interval", 0)
    rst_int = main_db.get_int("restart_interval", 0)
    viz_dir = main_db.get_string("viz_dirname", "viz_ex0")
    rst_dir = main_db.get_string("restart_dirname", "restart_ex0")
    os.makedirs(viz_dir, exist_ok=True)

    tm = TimerManager.instance()
    with MetricsLogger(main_db.get_string("log_file"), echo=True) as metrics:
        step = start_step
        while step < num_steps:
            chunk = min(viz_int or 50, num_steps - step)
            with tm.scope("IB::advanceHierarchy"):
                state = advance_ib(integ, state, dt, chunk)
                jax.block_until_ready(state.X)
            step += chunk
            metrics.log({
                "step": step,
                "t": state.ins.t,
                "area": polygon_area(state.X),
                "ke": integ.ins.kinetic_energy(state.ins),
                "max_div": integ.ins.max_divergence(state.ins),
                "cfl_dt": integ.ins.cfl_dt(state.ins),
            })
            if viz_int:
                np.savetxt(os.path.join(viz_dir, f"markers.{step:06d}.csv"),
                           np.asarray(state.X), delimiter=",")
            if rst_int and step % rst_int == 0:
                save_checkpoint(rst_dir, state, step)
    print(tm.report())
    return state


if __name__ == "__main__":
    main(sys.argv)
