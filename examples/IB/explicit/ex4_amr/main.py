"""ex4 with adaptive refinement: the 3D elastic shell in a background
stream, tracked by a marker-tagged refined window on the composite
two-level hierarchy (the reference's production adaptive-IB shape).

Run:  python examples/IB/explicit/ex4_amr/main.py [input3d]
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), *[".."] * 4))

from ibamr_tpu.utils.backend_guard import auto_backend  # noqa: E402

jax = auto_backend()

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from ibamr_tpu.amr import box_mac_to_cc  # noqa: E402
from ibamr_tpu.amr_ins import (TwoLevelIBINS,  # noqa: E402
                               advance_two_level_ib_regridding,
                               box_from_markers)
from ibamr_tpu.grid import StaggeredGrid  # noqa: E402
from ibamr_tpu.integrators.ib import IBMethod  # noqa: E402
from ibamr_tpu.io.vtk import VizWriter  # noqa: E402
from ibamr_tpu.models.shell3d import (make_spherical_shell,  # noqa: E402
                                      shell_volume)
from ibamr_tpu.ops import stencils  # noqa: E402
from ibamr_tpu.utils import (MetricsLogger, TimerManager,  # noqa: E402
                             parse_input_file)


def main(argv):
    input_path = argv[1] if len(argv) > 1 else \
        os.path.join(os.path.dirname(__file__), "input3d")
    db = parse_input_file(input_path)
    main_db = db.get_database("Main")
    ins_db = db.get_database("INSStaggeredHierarchyIntegrator")
    grid_db = db.get_database_with_default("GriddingAlgorithm")
    sh = db.get_database("Shell")
    geo = db.get_database("CartesianGeometry")

    grid = StaggeredGrid(
        n=tuple(int(v) for v in geo.get_int_array("n_cells")),
        x_lo=tuple(float(v) for v in geo.get_array("x_lo")),
        x_up=tuple(float(v) for v in geo.get_array("x_up")))

    center = tuple(float(v) for v in sh.get_array("center"))
    struct = make_spherical_shell(
        sh.get_int("n_lat"), sh.get_int("n_lon"), sh.get_float("radius"),
        center=center, stiffness=sh.get_float("stiffness"),
        rest_length_factor=sh.get_float("rest_length_factor", 1.0),
        aspect=sh.get_float("aspect", 1.0))
    dtype = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32
    ib = IBMethod(struct.force_specs(dtype=dtype),
                  kernel=db.get_database_with_default("IBMethod")
                  .get_string("delta_fcn", "IB_4"))

    X0 = jnp.asarray(struct.vertices, dtype)
    box = box_from_markers(grid, X0,
                           pad=grid_db.get_int("tag_buffer", 3))
    integ = TwoLevelIBINS(grid, box, ib,
                          rho=ins_db.get_float("rho", 1.0),
                          mu=ins_db.get_float("mu"),
                          proj_tol=1e-9 if dtype == jnp.float64
                          else 3e-6)
    u0 = db.get_database_with_default("Stream").get_float("u0", 0.0)
    state = integ.initialize(X0)
    fluid = state.fluid
    state = state._replace(fluid=fluid._replace(
        uc=(fluid.uc[0] + u0,) + fluid.uc[1:],
        uf=(fluid.uf[0] + u0,) + fluid.uf[1:]))

    dt = ins_db.get_float("dt")
    lim = float(integ.core.stable_dt(state.fluid))
    if dt > lim:
        print(f"WARNING: dt={dt:g} exceeds the explicit-predictor "
              f"stability advisory {lim:g} (finest-level viscous/CFL "
              "limit); expect blow-up")
    num_steps = ins_db.get_int("num_steps")
    regrid_int = grid_db.get_int("regrid_interval", 10)
    viz_int = main_db.get_int("viz_dump_interval", 0)
    viz_dir = main_db.get_string("viz_dirname", "viz_ex4_amr")
    metrics = MetricsLogger(main_db.get_string("log_file", "") or None)
    viz = VizWriter(viz_dir, grid)
    tm = TimerManager()

    v0 = float(shell_volume(state.X, center))
    last_viz = [0]

    def on_chunk(ci, cs, done):
        metrics.log({
            "step": done,
            "t": float(cs.fluid.t),
            "volume_drift": float(shell_volume(
                cs.X, tuple(np.mean(np.asarray(cs.X), axis=0)))) / v0
            - 1.0,
            "window_lo": list(ci.box.lo),
            "max_div": float(ci.core.max_divergence(cs.fluid)),
            "x_center": float(jnp.mean(cs.X[:, 0])),
        })
        if viz_int and done // viz_int > last_viz[0]:
            last_viz[0] = done // viz_int
            with tm.scope("Main::viz"):
                fg = ci.box.fine_grid(grid)
                viz.dump_hierarchy(
                    done, float(cs.fluid.t), [grid, fg],
                    [{"u": tuple(np.asarray(c) for c in
                                 stencils.fc_to_cc(cs.fluid.uc))},
                     {"u": tuple(np.asarray(c) for c in
                                 box_mac_to_cc(cs.fluid.uf))}],
                    fmt="binary")
                viz.dump(done, float(cs.fluid.t),
                         markers=np.asarray(cs.X))

    with tm.scope("IB::advanceHierarchy"):
        integ, state = advance_two_level_ib_regridding(
            integ, state, dt, num_steps, regrid_interval=regrid_int,
            on_chunk=on_chunk)
        jax.block_until_ready(state.X)
    print(tm.report())
    return integ, state


if __name__ == "__main__":
    main(sys.argv)
