"""ex0 with adaptive refinement: a 2D elastic membrane advected by a
background stream, tracked by a marker-tagged refined window on a
2-level composite hierarchy (the flagship AMR-IB user path:
TwoLevelIBINS + the host-side regrid cadence — the reference's
GriddingAlgorithm/StandardTagAndInitialize loop, SURVEY.md par.3.4).

Run:  python examples/IB/explicit/ex0_amr/main.py [input2d]
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), *[".."] * 4))

from ibamr_tpu.utils.backend_guard import auto_backend  # noqa: E402

jax = auto_backend()

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from ibamr_tpu.amr import box_mac_to_cc  # noqa: E402
from ibamr_tpu.amr_ins import (TwoLevelIBINS,  # noqa: E402
                               advance_two_level_ib_regridding,
                               box_from_markers)
from ibamr_tpu.ops import stencils  # noqa: E402
from ibamr_tpu.grid import StaggeredGrid  # noqa: E402
from ibamr_tpu.integrators.ib import IBMethod, polygon_area  # noqa: E402
from ibamr_tpu.models.membrane2d import make_circle_membrane  # noqa: E402
from ibamr_tpu.utils import (MetricsLogger, TimerManager,  # noqa: E402
                             parse_input_file)


def main(argv):
    input_path = argv[1] if len(argv) > 1 else \
        os.path.join(os.path.dirname(__file__), "input2d")
    db = parse_input_file(input_path)
    main_db = db.get_database("Main")
    ins_db = db.get_database("INSStaggeredHierarchyIntegrator")
    grid_db = db.get_database_with_default("GriddingAlgorithm")
    mem_db = db.get_database("Membrane")
    geo = db.get_database("CartesianGeometry")

    n = tuple(int(v) for v in geo.get_int_array("n_cells"))
    grid = StaggeredGrid(
        n=n,
        x_lo=tuple(float(v) for v in geo.get_array("x_lo")),
        x_up=tuple(float(v) for v in geo.get_array("x_up")))

    struct = make_circle_membrane(
        mem_db.get_int("num_markers"), mem_db.get_float("radius"),
        tuple(float(v) for v in mem_db.get_array("center")),
        stiffness=mem_db.get_float("stiffness"),
        rest_length_factor=mem_db.get_float("rest_length_factor", 1.0),
        aspect=mem_db.get_float("aspect", 1.0))
    # f32 on the accelerator like ex0 (enable jax x64 for an f64 run);
    # proj_tol sits above f32 roundoff so FGMRES terminates on the
    # tolerance, not the iteration cap
    dtype = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32
    ib = IBMethod(struct.force_specs(dtype=dtype),
                  kernel=db.get_database_with_default("IBMethod")
                  .get_string("delta_fcn", "IB_4"))

    X0 = jnp.asarray(struct.vertices, dtype)
    pad = grid_db.get_int("tag_buffer", 4)
    box = box_from_markers(grid, X0, pad=pad)
    integ = TwoLevelIBINS(grid, box, ib,
                          rho=ins_db.get_float("rho", 1.0),
                          mu=ins_db.get_float("mu"),
                          proj_tol=1e-9 if dtype == jnp.float64
                          else 3e-6)
    u0 = db.get_database_with_default("Stream").get_float("u0", 0.0)
    state = integ.initialize(X0)
    # background stream: a uniform (div-free) flow survives the
    # composite projection and advects the membrane
    fluid = state.fluid
    state = state._replace(fluid=fluid._replace(
        uc=(fluid.uc[0] + u0, fluid.uc[1]),
        uf=(fluid.uf[0] + u0, fluid.uf[1])))

    dt = ins_db.get_float("dt")
    num_steps = ins_db.get_int("num_steps")
    regrid_int = grid_db.get_int("regrid_interval", 20)
    viz_int = main_db.get_int("viz_dump_interval", 0)
    viz_dir = main_db.get_string("viz_dirname", "viz_ex0_amr")
    os.makedirs(viz_dir, exist_ok=True)
    metrics = MetricsLogger(main_db.get_string("log_file", "")
                            or None)
    from ibamr_tpu.io.vtk import VizWriter
    viz = VizWriter(viz_dir, grid)
    tm = TimerManager()

    a0 = float(polygon_area(state.X))
    last_viz = [0]

    def on_chunk(ci, cs, done):
        # host-side cadence hook: the regrid driver keeps its jit-chunk
        # cache alive across the whole run (a static window never
        # recompiles), and we observe/log between chunks. Viz/metrics
        # time is scoped separately from the advance scope.
        metrics.log({
            "step": done,
            "t": float(cs.fluid.t),
            "area_drift": float(polygon_area(cs.X)) / a0 - 1.0,
            "window_lo": list(ci.box.lo),
            "max_div": float(ci.core.max_divergence(cs.fluid)),
            "x_center": float(jnp.mean(cs.X[:, 0])),
        })
        if viz_int and done // viz_int > last_viz[0]:
            last_viz[0] = done // viz_int
            with tm.scope("Main::viz"):
                np.savetxt(os.path.join(viz_dir,
                                        f"markers.{done:06d}.csv"),
                           np.asarray(cs.X), delimiter=",")
                # hierarchy dump: coarse + window velocity at centers
                fg = ci.box.fine_grid(grid)
                viz.dump_hierarchy(done, float(cs.fluid.t), [grid, fg], [
                    {"u": tuple(np.asarray(c) for c in
                                stencils.fc_to_cc(cs.fluid.uc))},
                    {"u": tuple(np.asarray(c) for c in
                                box_mac_to_cc(cs.fluid.uf))}])

    with tm.scope("IB::advanceHierarchy"):
        integ, state = advance_two_level_ib_regridding(
            integ, state, dt, num_steps, regrid_interval=regrid_int,
            on_chunk=on_chunk)
        jax.block_until_ready(state.X)
    print(tm.report())
    return integ, state


if __name__ == "__main__":
    main(sys.argv)
