"""Flow-past-a-cylinder driver: target-point IB cylinder in an
inflow/outflow channel (reference: the external-flow IB examples over
the inflow/outflow-configured staggered INS integrator).

At the input file's Re_D = 50 the wake is on the edge of the vortex-
shedding instability; drag and transverse-force time series land in
the metrics JSONL for spectral inspection.

Run:  python examples/IB/explicit/cylinder2d/main.py [input2d]
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), *[".."] * 4))

from ibamr_tpu.utils.backend_guard import auto_backend  # noqa: E402

auto_backend()

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from ibamr_tpu.integrators.ib import IBMethod  # noqa: E402
from ibamr_tpu.integrators.ib_open import (IBOpenIntegrator,  # noqa: E402
                                           advance_ib_open)
from ibamr_tpu.integrators.ins_open import INSOpenIntegrator  # noqa: E402
from ibamr_tpu.io.vtk import VizWriter  # noqa: E402
from ibamr_tpu.ops.forces import ForceSpecs  # noqa: E402
from ibamr_tpu.solvers.stokes import channel_bc  # noqa: E402
from ibamr_tpu.utils import MetricsLogger, TimerManager, \
    parse_input_file  # noqa: E402


def main(argv):
    input_path = argv[1] if len(argv) > 1 else \
        os.path.join(os.path.dirname(__file__), "input2d")
    db = parse_input_file(input_path)
    main_db = db.get_database("Main")
    geo = db.get_database("CartesianGeometry")
    idb = db.get_database("INSOpenIntegrator")
    cyl = db.get_database("Cylinder")

    n = tuple(geo.get_int_array("n"))
    x_lo = tuple(geo.get_float_array("x_lo"))
    x_up = tuple(geo.get_float_array("x_up"))
    dx = tuple((u - l) / m for u, l, m in zip(x_up, x_lo, n))
    U0 = idb.get_float("U0")
    dt = idb.get_float("dt")
    ins = INSOpenIntegrator(
        n, dx, channel_bc(2), mu=idb.get_float("mu"), dt=dt,
        rho=idb.get_float("rho", 1.0), bdry={(0, 0, 0): U0},
        tol=idb.get_float("tol", 1e-7),
        convective_op_type=idb.get_string("convective_op_type",
                                          "stabilized_ppm"),
        dtype=jnp.float32)   # production dtype (silences f64->f32
#                              truncation warnings on TPU/CPU-x32)

    cx, cy = cyl.get_float_array("center")
    D = cyl.get_float("diameter")
    m = cyl.get_int("n_markers")
    th = 2.0 * np.pi * np.arange(m) / m
    X0 = jnp.asarray(np.stack([cx + 0.5 * D * np.cos(th),
                               cy + 0.5 * D * np.sin(th)], axis=1),
                     dtype=jnp.float32)
    kappa = cyl.get_float("kappa")
    eta = cyl.get_float("eta")
    ib = IBMethod(ForceSpecs(), kernel="IB_4",
                  force_fn=lambda X, U, t: -kappa * (X - X0) - eta * U)
    integ = IBOpenIntegrator(ins, ib, x_lo=x_lo)
    st = integ.initialize(X0)

    viz_dir = main_db.get_string("viz_dirname", "viz_cylinder2d")
    os.makedirs(viz_dir, exist_ok=True)
    writer = VizWriter(viz_dir, integ.grid)
    metrics = MetricsLogger(main_db.get_string("log_jsonl",
                                               "cylinder2d_metrics.jsonl"))
    timers = TimerManager()
    num_steps = idb.get_int("num_steps")
    viz_int = main_db.get_int("viz_dump_interval", 0)
    chunk = viz_int if viz_int else num_steps

    k = 0
    while k < num_steps:
        mstep = min(chunk, num_steps - k)
        with timers.scope("advance"):
            st = advance_ib_open(integ, st, mstep)
            jax.block_until_ready(st.X)
        k += mstep
        F = integ.body_force_on_fluid(st)
        drag = -float(F[0])
        lift = -float(F[1])
        cd = drag / (0.5 * ins.rho * U0 ** 2 * D)
        metrics.log({"step": k, "t": float(st.fluid.t),
                     "drag": drag, "lift": lift, "cd": cd})
        print(f"step {k}: t={float(st.fluid.t):.3f} "
              f"cd={cd:.3f} lift={lift:+.4f}")
        if viz_int:
            u_low = integ._to_lower(st.fluid.u)
            writer.dump(k, float(st.fluid.t),
                        cell_fields={"u": np.asarray(u_low[0]),
                                     "v": np.asarray(u_low[1]),
                                     "p": np.asarray(st.fluid.p)},
                        markers=np.asarray(st.X))
    timers.report()


if __name__ == "__main__":
    main(sys.argv)
