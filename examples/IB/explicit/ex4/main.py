"""ex4-equivalent driver: 3D elastic shell in incompressible flow
(reference: examples/IB/explicit/ex4 main.cpp + input3d).

Run:  python examples/IB/explicit/ex4/main.py [input3d] [restart_dir step]
Multi-device: the Eulerian grid shards over all visible devices
automatically when more than one device is present (spatial domain
decomposition + the S2 sharded marker transfers).

The advance/viz/restart/health loop is the shared HierarchyDriver
skeleton (T13); this file is config + callbacks only.
"""

import os
import sys

import jax
import jax.numpy as jnp

sys.path.insert(0, os.path.join(os.path.dirname(__file__), *[".."] * 4))

# backend guard BEFORE any jax compute: honors JAX_PLATFORMS=cpu
# (defeating the axon sitecustomize override) and probes the TPU
# relay with a timeout instead of hanging when it is down
from ibamr_tpu.utils.backend_guard import auto_backend  # noqa: E402

auto_backend()

import numpy as np  # noqa: E402

from ibamr_tpu.models.shell3d import build_shell_example, shell_volume  # noqa: E402
from ibamr_tpu.utils import MetricsLogger, TimerManager, parse_input_file  # noqa: E402
from ibamr_tpu.utils.checkpoint import restore_checkpoint, save_checkpoint  # noqa: E402
from ibamr_tpu.utils.hierarchy_driver import HierarchyDriver, RunConfig  # noqa: E402


def main(argv):
    input_path = argv[1] if len(argv) > 1 else \
        os.path.join(os.path.dirname(__file__), "input3d")
    db = parse_input_file(input_path)
    main_db = db.get_database("Main")
    ins_db = db.get_database("INSStaggeredHierarchyIntegrator")

    integ, state = build_shell_example(input_db=db, dtype=jnp.float32)

    # shard over all devices when more than one is visible
    if len(jax.devices()) > 1:
        from ibamr_tpu.parallel import make_mesh, make_sharded_ib_step
        from ibamr_tpu.parallel.mesh import place_state

        mesh = make_mesh()
        state = place_state(state, integ.ins.grid, mesh)
        step_fn = make_sharded_ib_step(integ, mesh)
        print(f"sharding over mesh {dict(mesh.shape)}")
    else:
        step_fn = jax.jit(lambda s, d: integ.step(s, d))

    start_step = 0
    if len(argv) > 3:
        state, start_step, _ = restore_checkpoint(argv[2], state,
                                                  step=int(argv[3]))
        print(f"restarted from {argv[2]} at step {start_step}")

    viz_dir = main_db.get_string("viz_dirname", "viz_ex4")
    rst_dir = main_db.get_string("restart_dirname", "restart_ex4")
    os.makedirs(viz_dir, exist_ok=True)
    geo = db.get_database_with_default("CartesianGeometry")
    x_lo = geo.get_array("x_lo", [0.0, 0.0, 0.0])
    x_up = geo.get_array("x_up", [1.0, 1.0, 1.0])
    center = tuple(0.5 * (lo + hi) for lo, hi in zip(x_lo, x_up))

    viz_int = main_db.get_int("viz_dump_interval", 0)
    cfg = RunConfig(
        dt=ins_db.get_float("dt"),
        num_steps=ins_db.get_int("num_steps"),
        viz_dump_interval=viz_int,
        restart_interval=main_db.get_int("restart_interval", 0),
        health_interval=min(20, viz_int) if viz_int else 20)

    tm = TimerManager.instance()
    with MetricsLogger(main_db.get_string("log_file"), echo=True) as log:

        def metrics_fn(s, step):
            rec = {
                "step": step,
                "t": s.ins.t,
                "volume": shell_volume(s.X, center),
                "ke": integ.ins.kinetic_energy(s.ins),
                "max_div": integ.ins.max_divergence(s.ins),
                "cfl_dt": integ.ins.cfl_dt(s.ins),
            }
            log.log(rec)
            return rec

        def viz_fn(s, step):
            np.savetxt(os.path.join(viz_dir, f"markers.{step:06d}.csv"),
                       np.asarray(s.X), delimiter=",")

        driver = HierarchyDriver(
            integ, cfg, step_fn=step_fn, metrics_fn=metrics_fn,
            viz_fn=viz_fn,
            checkpoint_fn=lambda s, k: save_checkpoint(rst_dir, s, k),
            timer=tm, timer_name="IB::advanceHierarchy")
        state = driver.run(state, start_step=start_step)
    print(tm.report())
    return state


if __name__ == "__main__":
    main(sys.argv)
