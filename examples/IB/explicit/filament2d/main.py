"""Flapping-filament driver: a near-inextensible elastic fiber
(stretching springs + bending beams) anchored at its leading end in a
uniform stream — the canonical flexible-structure IB example
(reference: the filament/flag examples over the inflow-configured
staggered INS integrator; Zhu & Peskin 2002). Beyond the critical
length the trailing end sustains self-excited flapping; the tail's
transverse position time series lands in the metrics JSONL.

Run:  python examples/IB/explicit/filament2d/main.py [input2d]
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), *[".."] * 4))

from ibamr_tpu.utils.backend_guard import auto_backend  # noqa: E402

auto_backend()

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from ibamr_tpu.integrators.ib import IBMethod  # noqa: E402
from ibamr_tpu.integrators.ib_open import (IBOpenIntegrator,  # noqa: E402
                                           advance_ib_open)
from ibamr_tpu.integrators.ins_open import INSOpenIntegrator  # noqa: E402
from ibamr_tpu.io.vtk import VizWriter  # noqa: E402
from ibamr_tpu.ops.forces import (ForceSpecs, make_beams,  # noqa: E402
                                  make_springs, make_targets)
from ibamr_tpu.solvers.stokes import channel_bc  # noqa: E402
from ibamr_tpu.utils import MetricsLogger, TimerManager, \
    parse_input_file  # noqa: E402


def build_filament(fil, dtype=jnp.float32):
    """Marker chain + stretching springs + bending beams + the
    leading-end anchor (the .vertex/.spring/.beam/.target menu the
    reference's IBStandardInitializer reads, assembled in code)."""
    ax, ay = fil.get_float_array("anchor")
    L = fil.get_float("length")
    m = fil.get_int("n_markers")
    inc = fil.get_float("incline", 0.0)
    s = np.linspace(0.0, L, m)
    X0 = np.stack([ax + s * np.cos(inc), ay + s * np.sin(inc)],
                  axis=1)
    ds = L / (m - 1)
    springs = make_springs(np.arange(m - 1), np.arange(1, m),
                           np.full(m - 1, fil.get_float("k_stretch")),
                           np.full(m - 1, ds), dtype=dtype)
    beams = make_beams(np.arange(m - 2), np.arange(1, m - 1),
                       np.arange(2, m),
                       np.full(m - 2, fil.get_float("k_bend")),
                       dim=2, dtype=dtype)
    targets = make_targets(np.array([0]),
                           np.array([fil.get_float("k_anchor")]),
                           X0[:1], dtype=dtype)
    specs = ForceSpecs(springs=springs, beams=beams, targets=targets)
    return jnp.asarray(X0, dtype=dtype), specs


def main(argv):
    input_path = argv[1] if len(argv) > 1 else \
        os.path.join(os.path.dirname(__file__), "input2d")
    db = parse_input_file(input_path)
    main_db = db.get_database("Main")
    geo = db.get_database("CartesianGeometry")
    idb = db.get_database("INSOpenIntegrator")
    fil = db.get_database("Filament")

    n = tuple(geo.get_int_array("n"))
    x_lo = tuple(geo.get_float_array("x_lo"))
    x_up = tuple(geo.get_float_array("x_up"))
    dx = tuple((u - l) / m for u, l, m in zip(x_up, x_lo, n))
    U0 = idb.get_float("U0")
    dt = idb.get_float("dt")
    ins = INSOpenIntegrator(
        n, dx, channel_bc(2), mu=idb.get_float("mu"), dt=dt,
        rho=idb.get_float("rho", 1.0), bdry={(0, 0, 0): U0},
        tol=idb.get_float("tol", 1e-7),
        convective_op_type=idb.get_string("convective_op_type",
                                          "stabilized_ppm"),
        dtype=jnp.float32)

    X0, specs = build_filament(fil)
    ib = IBMethod(specs, kernel="IB_4")
    integ = IBOpenIntegrator(ins, ib, x_lo=x_lo)
    st = integ.initialize(X0)

    viz_dir = main_db.get_string("viz_dirname", "viz_filament2d")
    os.makedirs(viz_dir, exist_ok=True)
    writer = VizWriter(viz_dir, integ.grid)
    metrics = MetricsLogger(main_db.get_string(
        "log_jsonl", "filament2d_metrics.jsonl"))
    timers = TimerManager()
    num_steps = idb.get_int("num_steps")
    viz_int = main_db.get_int("viz_dump_interval", 0)
    chunk = min(50, viz_int) if viz_int else 50

    k = 0
    while k < num_steps:
        mstep = min(chunk, num_steps - k)
        with timers.scope("advance"):
            st = advance_ib_open(integ, st, mstep)
            jax.block_until_ready(st.X)
        k += mstep
        tail = np.asarray(st.X[-1])
        F = integ.body_force_on_fluid(st)
        metrics.log({"step": k, "t": float(st.fluid.t),
                     "tail_x": float(tail[0]), "tail_y": float(tail[1]),
                     "drag": -float(F[0]), "lift": -float(F[1])})
        print(f"step {k}: t={float(st.fluid.t):.3f} "
              f"tail_y={float(tail[1]):+.4f}")
        if viz_int and k % viz_int == 0:
            u_low = integ._to_lower(st.fluid.u)
            writer.dump(k, float(st.fluid.t),
                        cell_fields={"u": np.asarray(u_low[0]),
                                     "v": np.asarray(u_low[1]),
                                     "p": np.asarray(st.fluid.p)},
                        markers=np.asarray(st.X))
    timers.report()


if __name__ == "__main__":
    main(sys.argv)
